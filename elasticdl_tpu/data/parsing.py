"""Batch-level record parsing: the vectorized half of the input pipeline.

Reference parity: the reference's dataset_fn returned a tf.data transform
whose decode ops ran as C++ kernels inside the tf.data runtime (SURVEY §2.4
data readers, §3.3); records were never touched one at a time by Python. The
rebuild's first cut parsed per record in Python, which capped the pipeline
~26x below the chip (BASELINE.md round-2 row). This module restores the
batch-at-a-time contract:

- A *batch parser* is a callable `parse_batch(records: Sequence[bytes]) ->
  (features, labels)` returning already-stacked numpy arrays, marked with
  `is_batch_parser = True` (use the `batch_parser` decorator). dataset_fn may
  return one instead of a per-record parser; TaskDataService detects the mark
  and feeds whole batches.
- `as_batch_parser(parse)` upgrades any per-record parser to the batch
  interface (Python-loop fallback, same behavior as before).
- `criteo_batch_parser()` / `numeric_batch_parser()` / `u8_image_batch_parser()`
  call the C++ kernels in native/batch_parse.cc via ctypes (GIL released →
  parser threads scale across cores), with numpy/Python fallbacks when the
  native library is unavailable.

The wire layout shared with C++: records are concatenated into one buffer
with an int64 offsets array of length n+1; record i is buf[off[i], off[i+1]).
"""

from __future__ import annotations

import ctypes
from typing import Any, Callable, List, Sequence, Tuple

import numpy as np

from elasticdl_tpu.data import nativelib

BatchParser = Callable[[Sequence[bytes]], Tuple[Any, Any]]


def _int0(p: str) -> int:
    """Malformed/empty field -> 0, matching the C++ kernels' stance
    (batch_parse.cc degrades bad bytes to zeros rather than failing the
    batch); without this the pure-Python fallback's behavior would depend on
    whether the host has a toolchain."""
    try:
        return int(p)
    except ValueError:
        return 0


def _float0(p: str) -> float:
    try:
        v = float(p)
        return v if np.isfinite(v) else 0.0
    except ValueError:
        return 0.0


def _hex0(p: str) -> int:
    try:
        return int(p, 16) & 0x7FFFFFFF
    except ValueError:
        return 0


_lib = None
_lib_loaded = False


def _load() -> Any:
    global _lib, _lib_loaded
    if _lib_loaded:
        return _lib
    _lib_loaded = True
    lib = nativelib.load_shared("batch_parse")
    if lib is not None:
        i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
        i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
        f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
        lib.edl_parse_criteo.restype = ctypes.c_int
        lib.edl_parse_criteo.argtypes = [
            ctypes.c_char_p, i64p, ctypes.c_int64,
            ctypes.c_int, ctypes.c_int, i32p, f32p, i32p,
        ]
        lib.edl_parse_numeric.restype = ctypes.c_int
        lib.edl_parse_numeric.argtypes = [
            ctypes.c_char_p, i64p, ctypes.c_int64, ctypes.c_char,
            ctypes.c_int, ctypes.c_int, ctypes.c_int, i32p, f32p,
        ]
        lib.edl_parse_u8_image.restype = ctypes.c_int
        lib.edl_parse_u8_image.argtypes = [
            ctypes.c_char_p, i64p, ctypes.c_int64,
            ctypes.c_int, ctypes.c_float, i32p, f32p,
        ]
    _lib = lib
    return _lib


def pack_records(records: Sequence[bytes]) -> Tuple[bytes, np.ndarray]:
    """Concatenate records; return (buffer, int64 offsets[n+1])."""
    offs = np.empty(len(records) + 1, np.int64)
    offs[0] = 0
    np.cumsum([len(r) for r in records], out=offs[1:])
    return b"".join(records), offs


def batch_parser(fn: BatchParser) -> BatchParser:
    """Mark `fn` as batch-level so TaskDataService skips the per-record path."""
    fn.is_batch_parser = True  # type: ignore[attr-defined]
    return fn


def is_batch_parser(fn: Callable) -> bool:
    return bool(getattr(fn, "is_batch_parser", False))


def as_batch_parser(parse: Callable[[bytes], Tuple[Any, Any]]) -> BatchParser:
    """Upgrade a per-record parser to the batch interface (loop fallback)."""
    if is_batch_parser(parse):
        return parse  # already batch-level

    def _stack(values: List[Any]):
        if isinstance(values[0], dict):
            return {k: _stack([v[k] for v in values]) for k in values[0]}
        return np.stack(values)

    @batch_parser
    def parse_batch(records: Sequence[bytes]):
        feats, labels = zip(*(parse(r) for r in records))
        return _stack(list(feats)), _stack(list(labels))

    return parse_batch


def criteo_batch_parser(num_dense: int = 13, num_cat: int = 26) -> BatchParser:
    """Criteo TSV (label \\t ints \\t hex cats) -> {"dense","cat"}, labels.
    Matches model_zoo/deepfm's per-record parser bit-for-bit (tested)."""

    @batch_parser
    def parse_batch(records: Sequence[bytes]):
        n = len(records)
        labels = np.empty(n, np.int32)
        dense = np.empty((n, num_dense), np.float32)
        cat = np.empty((n, num_cat), np.int32)
        lib = _load()
        if lib is not None:
            buf, offs = pack_records(records)
            lib.edl_parse_criteo(buf, offs, n, num_dense, num_cat,
                                 labels, dense, cat)
        else:
            for i, record in enumerate(records):
                parts = record.decode("utf-8", errors="replace").rstrip("\n").split("\t")
                labels[i] = _int0(parts[0])
                drow = parts[1:1 + num_dense]
                dense[i] = [_float0(p) for p in drow] + [0.0] * (
                    num_dense - len(drow)
                )
                crow = parts[1 + num_dense:][:num_cat]
                cat[i] = [_hex0(p) for p in crow] + [0] * (num_cat - len(crow))
        return {"dense": dense, "cat": cat}, labels

    return parse_batch


def criteo_bin_record_bytes(num_dense: int = 13, num_cat: int = 26) -> int:
    """Fixed-width binary Criteo record: int32 label + num_dense float32 +
    num_cat int32, little-endian."""
    return 4 * (1 + num_dense + num_cat)


def criteo_bin_encode(labels, dense, cat) -> bytes:
    """Encode parsed Criteo arrays into the fixed-width binary layout
    (the ingest half of the binary fast path; see criteo_bin_batch_parser)."""
    n = len(labels)
    num_dense = dense.shape[1]
    num_cat = cat.shape[1]
    out = np.empty((n, 1 + num_dense + num_cat), np.int32)
    out[:, 0] = labels
    out[:, 1:1 + num_dense].view(np.float32)[:] = dense
    out[:, 1 + num_dense:] = cat
    return out.tobytes()


def criteo_bin_batch_parser(num_dense: int = 13, num_cat: int = 26) -> BatchParser:
    """Decode fixed-width binary Criteo records at memcpy speed.

    Why this exists: Criteo-as-TSV costs ~250 text bytes/sample and parsing
    text is compute-bound (~0.9M rec/s/core measured here — this sandbox has
    ONE host core; see BASELINE.md). The reference solved the same problem by
    training from binary RecordIO shards, not raw text (SURVEY §2.4/§2.7
    item 3). This is the rebuild's equivalent: `convert_criteo_tsv` turns TSV
    into .cbin shards once at ingest (using the C++ text parser), and the
    training-time "parse" is one numpy reinterpret over the span — no
    per-field work at all. Accepts either a record list or a contiguous blob
    (`accepts_blob`, used with FixedLenBinDataReader.read_block to skip
    record splitting entirely).
    """
    words = 1 + num_dense + num_cat

    @batch_parser
    def parse_batch(records):
        blob = records if isinstance(records, (bytes, bytearray, memoryview)) \
            else b"".join(records)
        full = np.frombuffer(blob, "<i4").reshape(-1, words)
        labels = np.ascontiguousarray(full[:, 0])
        dense = np.ascontiguousarray(full[:, 1:1 + num_dense]).view(np.float32)
        cat = np.ascontiguousarray(full[:, 1 + num_dense:])
        return {"dense": dense, "cat": cat}, labels

    parse_batch.accepts_blob = True  # type: ignore[attr-defined]
    return parse_batch


def convert_criteo_tsv(
    src_path: str, dst_dir: str, records_per_shard: int = 1 << 20,
    num_dense: int = 13, num_cat: int = 26, parse_chunk: int = 65536,
) -> List[str]:
    """One-time ingest: Criteo TSV file/dir/glob -> fixed-width .cbin shards
    in `dst_dir`. Returns the shard paths. Text parsing happens here, once,
    through the C++ kernel — training then reads binary forever after (the
    RecordIO conversion step of the reference's data prep, SURVEY §2.7)."""
    import os

    from elasticdl_tpu.data.reader import TextLineDataReader

    reader = TextLineDataReader(src_path)
    text_parse = criteo_batch_parser(num_dense, num_cat)
    os.makedirs(dst_dir, exist_ok=True)
    paths: List[str] = []
    out = None
    out_count = 0

    def finish_current():
        """Close and atomically publish the in-progress shard: a crash mid-
        convert must never leave a truncated file under the final name (the
        fixed-width reader would reject — or worse, misread — it)."""
        nonlocal out
        if out is not None:
            out.close()
            os.replace(paths[-1] + ".tmp", paths[-1])
            out = None

    def rotate():
        nonlocal out, out_count
        finish_current()
        p = os.path.join(dst_dir, f"criteo-{len(paths):05d}.cbin")
        paths.append(p)
        out = open(p + ".tmp", "wb")
        out_count = 0

    rotate()
    for shard_name, start, end in reader.create_shards():
        for s in range(start, end, parse_chunk):
            records = reader.read_span(shard_name, s, min(s + parse_chunk, end))
            feats, labels = text_parse(records)
            pos, n = 0, len(labels)
            while pos < n:
                take = min(records_per_shard - out_count, n - pos)
                out.write(criteo_bin_encode(
                    labels[pos:pos + take],
                    feats["dense"][pos:pos + take],
                    feats["cat"][pos:pos + take],
                ))
                out_count += take
                pos += take
                if out_count >= records_per_shard:
                    rotate()
    finish_current()
    if out_count == 0 and len(paths) > 1:  # drop the empty trailing shard
        os.remove(paths.pop())
    return paths


def numeric_batch_parser(
    num_cols: int, sep: str = ",", label_col: int = -1,
    exclude_label: bool = True,
) -> BatchParser:
    """Delimited numeric table -> float32 matrix (+ int32 labels column)."""

    @batch_parser
    def parse_batch(records: Sequence[bytes]):
        n = len(records)
        out_cols = num_cols - (1 if exclude_label and label_col >= 0 else 0)
        labels = np.zeros(n, np.int32)
        out = np.empty((n, out_cols), np.float32)
        lib = _load()
        if lib is not None:
            buf, offs = pack_records(records)
            lib.edl_parse_numeric(
                buf, offs, n, sep.encode(), num_cols, label_col,
                int(exclude_label), labels, out,
            )
        else:
            for i, record in enumerate(records):
                parts = record.decode("utf-8", errors="replace").strip().split(sep)
                vals = [_float0(p) for p in parts[:num_cols]]
                vals += [0.0] * (num_cols - len(vals))
                if label_col >= 0:
                    labels[i] = int(vals[label_col])
                    if exclude_label:
                        vals = vals[:label_col] + vals[label_col + 1:]
                out[i] = vals
        return out, labels

    return parse_batch


def u8_image_batch_parser(
    width: int, shape: Tuple[int, ...] = (), scale: float = 1.0 / 255.0,
) -> BatchParser:
    """Fixed-width binary records (1 label byte + `width` uint8 pixels) ->
    float32 images scaled by `scale`, reshaped to (n, *shape)."""

    @batch_parser
    def parse_batch(records: Sequence[bytes]):
        n = len(records)
        labels = np.empty(n, np.int32)
        out = np.empty((n, width), np.float32)
        lib = _load()
        if lib is not None:
            buf, offs = pack_records(records)
            rc = lib.edl_parse_u8_image(buf, offs, n, width,
                                        np.float32(scale), labels, out)
            if rc != 0:
                raise ValueError("u8_image record shorter than 1+width bytes")
        else:
            for i, record in enumerate(records):
                if len(record) < 1 + width:
                    raise ValueError("u8_image record shorter than 1+width bytes")
                labels[i] = record[0]
                out[i] = np.frombuffer(record, np.uint8, width, 1) * scale
        if shape:
            out = out.reshape((n, *shape))
        return out, labels

    return parse_batch
