"""Convert any data source into EDLR recordio shards.

Role parity: the reference ships `scripts` that convert MNIST/CIFAR datasets
into RecordIO shards for its model zoo. `convert_to_recordio` turns any
AbstractDataReader (including the synthetic generators) into .rio shard
files, so benches and jobs exercise the real native read path.
"""

from __future__ import annotations

import os
from typing import List

from elasticdl_tpu.common.log_utils import default_logger
from elasticdl_tpu.data.reader import AbstractDataReader, create_data_reader
from elasticdl_tpu.data.recordio import RecordIOWriter

logger = default_logger(__name__)


def convert_to_recordio(
    reader: AbstractDataReader,
    out_dir: str,
    records_per_shard: int = 50_000,
    chunk_bytes: int = 1 << 20,
) -> List[str]:
    """Write every record of `reader` into .rio shards under out_dir."""
    os.makedirs(out_dir, exist_ok=True)
    files: List[str] = []
    writer = None
    count_in_shard = 0
    total = 0

    def new_writer() -> RecordIOWriter:
        path = os.path.join(out_dir, f"part-{len(files):05d}.rio")
        files.append(path)
        return RecordIOWriter(path, chunk_bytes=chunk_bytes)

    for shard_name, start, end in reader.create_shards():
        for record in reader.read_records(shard_name, start, end):
            if writer is None:
                writer = new_writer()
            writer.write(record)
            count_in_shard += 1
            total += 1
            if count_in_shard >= records_per_shard:
                writer.close()
                writer = None
                count_in_shard = 0
    if writer is not None:
        writer.close()
    logger.info("wrote %d records into %d shards under %s", total, len(files), out_dir)
    return files


def convert_url(
    source: str, out_dir: str, records_per_shard: int = 50_000
) -> List[str]:
    """Convenience: convert a reader URL/path (e.g. synthetic://criteo?n=1e6)."""
    return convert_to_recordio(
        create_data_reader(source), out_dir, records_per_shard
    )
