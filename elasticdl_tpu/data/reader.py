"""Data readers: shard discovery + record reading for the task queue.

Reference parity: elasticdl/python/common/data_reader.py —
`AbstractDataReader.create_shards()` lists (shard_name, start, end) spans the
master turns into tasks, and `read_records(task)` yields the records of one
task on the worker. Implementations: RecordIO (native), ODPS table, CSV.

Rebuilt: TextLine (CSV/TSV), RecordIO (C++ reader in data/native once built,
with a pure-Python twin of the same format), and Synthetic readers that
deterministically generate MNIST/CIFAR/Criteo/census-shaped records so every
parity config trains self-contained (this sandbox has no dataset downloads;
the reference assumed data already in storage).
"""

from __future__ import annotations

import glob
import os
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

Shard = Tuple[str, int, int]


def resolve_files(
    path: str,
    exclude_suffix: str = "",
    require_suffix: str = "",
) -> List[str]:
    """Glob / file / directory → sorted file list (shared by the file-backed
    readers). `require_suffix` filters dir/glob listings to one extension
    (fixed-width readers must not reinterpret stray files as records);
    `exclude_suffix` drops sidecar files (.edlidx.npy indexes)."""
    if any(c in path for c in "*?["):
        files = glob.glob(path)
    elif os.path.isfile(path):
        return [path]   # an explicit single path is always taken verbatim
    elif os.path.isdir(path):
        files = [os.path.join(path, f) for f in os.listdir(path)]
    else:
        return []
    if require_suffix:
        files = [f for f in files if f.endswith(require_suffix)]
    if exclude_suffix:
        files = [f for f in files if not f.endswith(exclude_suffix)]
    return sorted(files)


class AbstractDataReader:
    def create_shards(self) -> List[Shard]:
        """List (shard_name, start_record, end_record) spans."""
        raise NotImplementedError

    def read_records(self, shard_name: str, start: int, end: int) -> Iterator[bytes]:
        """Yield records [start, end) of one shard."""
        raise NotImplementedError

    def read_span(self, shard_name: str, start: int, end: int) -> List[bytes]:
        """Materialize records [start, end) as a list — the batch-pipeline
        entry point (TaskDataService reads batch-sized spans). File-backed
        readers override this with one contiguous read + vectorized split;
        the default just drains the per-record generator."""
        return list(self.read_records(shard_name, start, end))

    def read_block(self, shard_name: str, start: int, end: int) -> Optional[bytes]:
        """Records [start, end) as ONE contiguous byte blob, or None when the
        format can't provide it. Only fixed-width formats support this; it
        lets blob-accepting batch parsers (parsing.py `accepts_blob`) skip
        record splitting entirely."""
        return None

    # Readers whose read_span/read_block may be called from MULTIPLE threads
    # concurrently set this True (TaskDataService's parse pool checks it;
    # readers sharing per-shard handles/caches, like RecordIO, stay serial).
    THREAD_SAFE_SPANS = False

    @property
    def metadata(self) -> Dict:
        return {}


class TextLineDataReader(AbstractDataReader):
    """Newline-delimited files (CSV/TSV). Shard = file; record = line.

    Line offsets are indexed once per file on first read so seeks are O(1)
    afterwards (the role RecordIO's chunk index plays for binary records).
    """

    INDEX_SUFFIX = ".edlidx.npy"
    # read_span opens its own handle per call and _index is lock-guarded, so
    # the parse pool may fan spans of one shard across threads
    THREAD_SAFE_SPANS = True

    def __init__(self, path: str, skip_header: bool = False,
                 index_cache: bool = True, **_):
        import threading

        # exclude .edlidx.npy sidecars in dir AND glob listings: a pattern
        # like 'part-*' matches the sidecars a previous run wrote
        self._files = resolve_files(path, exclude_suffix=self.INDEX_SUFFIX)
        if not self._files:
            raise FileNotFoundError(f"no input files match {path!r}")
        self._skip_header = skip_header
        self._index_cache = index_cache
        self._offsets: Dict[str, np.ndarray] = {}
        # one thread builds a file's index; others wait instead of racing
        # duplicate scans + colliding on the sidecar tmp path
        self._index_lock = threading.Lock()

    SCAN_WINDOW = 64 << 20  # 64 MB

    def _scan_index(self, fname: str) -> np.ndarray:
        """All line-start offsets + EOF, found with vectorized newline scans
        over fixed-size windows (C speed, O(window) memory — a whole-file
        bool mask would transiently cost one byte per data byte, fatal on
        Criteo-sized TSVs)."""
        size = os.path.getsize(fname)
        if size == 0:
            return np.zeros(1, np.int64)
        parts = []
        # the index lock EXISTS to serialize this once-per-file scan
        # (concurrent readers must pay one scan, not one each):
        # edl-lint: disable=EDL103
        with open(fname, "rb") as f:
            pos = 0
            while True:
                chunk = f.read(self.SCAN_WINDOW)
                if not chunk:
                    break
                nl = np.flatnonzero(np.frombuffer(chunk, np.uint8) == 0x0A)
                if nl.size:
                    parts.append(nl.astype(np.int64) + pos)
                pos += len(chunk)
        nl = np.concatenate(parts) if parts else np.empty(0, np.int64)
        starts = np.concatenate([[0], nl + 1])
        if starts[-1] != size:  # last line has no trailing newline
            starts = np.concatenate([starts, [size]])
        return starts

    def _index(self, fname: str) -> np.ndarray:
        """Line-offset index, persisted to a sidecar `.edlidx.npy` so each
        file is scanned once per cluster, not once per process per run (the
        role RecordIO's footer index plays for binary shards). The sidecar is
        ignored when older than the data file; writing it is best-effort
        (read-only input dirs just re-scan)."""
        if fname in self._offsets:
            return self._offsets[fname]
        with self._index_lock:
            if fname in self._offsets:   # built while we waited
                return self._offsets[fname]
            idx_path = fname + self.INDEX_SUFFIX
            offs = None
            if self._index_cache and os.path.exists(idx_path):
                try:
                    if os.path.getmtime(idx_path) >= os.path.getmtime(fname):
                        cand = np.load(idx_path)
                        if cand.ndim == 1 and cand.size >= 1 and (
                            int(cand[-1]) == os.path.getsize(fname)
                        ):
                            offs = cand.astype(np.int64)
                except (OSError, ValueError):
                    offs = None
            if offs is None:
                offs = self._scan_index(fname)
                if self._index_cache:
                    # the temp name ENDS in the sidecar suffix (a crashed
                    # writer's orphan, or a mid-write listing, is excluded
                    # from data-file resolution like the final sidecar) and
                    # carries pid+thread id: same-file writers in OTHER
                    # processes must not collide either
                    import threading

                    tmp = (
                        f"{idx_path}.{os.getpid()}-{threading.get_ident()}"
                        f".tmp{self.INDEX_SUFFIX}"
                    )
                    try:
                        # sidecar persist rides the same once-per-file
                        # index window: edl-lint: disable=EDL103
                        with open(tmp, "wb") as f:
                            np.save(f, offs)
                        os.replace(tmp, idx_path)
                    except OSError:
                        pass
                    finally:
                        if os.path.exists(tmp):
                            try:
                                os.remove(tmp)
                            except OSError:
                                pass
            start = 1 if self._skip_header else 0
            self._offsets[fname] = offs[start:]
            return self._offsets[fname]

    def create_shards(self) -> List[Shard]:
        return [
            (f, 0, len(self._index(f)) - 1)
            for f in self._files
        ]

    def read_span(self, shard_name: str, start: int, end: int) -> List[bytes]:
        offs = self._index(shard_name)
        end = min(end, len(offs) - 1)
        if start >= end:
            return []
        with open(shard_name, "rb") as f:
            f.seek(offs[start])
            blob = f.read(int(offs[end] - offs[start]))
        base = int(offs[start])
        return [
            blob[int(offs[i]) - base: int(offs[i + 1]) - base].rstrip(b"\r\n")
            for i in range(start, end)
        ]

    def read_records(self, shard_name: str, start: int, end: int) -> Iterator[bytes]:
        """Streaming per-record path: O(1) memory regardless of span size —
        callers like data/convert.py iterate WHOLE-FILE shards here, where
        read_span's one-blob materialization would hold the file (+ a line
        list) in memory. The batch pipeline uses read_span on batch-sized
        spans instead."""
        offs = self._index(shard_name)
        end = min(end, len(offs) - 1)
        if start >= end:
            return
        with open(shard_name, "rb") as f:
            f.seek(offs[start])
            for _ in range(start, end):
                yield f.readline().rstrip(b"\r\n")


class CSVDataReader(TextLineDataReader):
    """CSV with a header row: column names surface through `metadata` so
    dataset_fn parsers can address fields by name instead of position
    (reference parity: the CSV reader used by the census/wide-deep configs).
    Records are the raw data lines; parsing stays in the model's dataset_fn.
    """

    def __init__(
        self,
        path: str,
        delimiter: str = ",",
        columns: Optional[List[str]] = None,
        **params,
    ):
        params.pop("skip_header", None)
        super().__init__(path, skip_header=True, **params)
        self._delimiter = delimiter

        def header_of(fname: str) -> List[str]:
            with open(fname, "rb") as f:
                header = f.readline().decode().rstrip("\r\n")
            return [c.strip() for c in header.split(delimiter)]

        first_header = header_of(self._files[0])
        # Explicit columns= RENAMES the schema (reference behavior); the
        # physical headers must still agree file-to-file: a directory mixing
        # column orders would otherwise be silently misparsed — positions,
        # not names, address fields after the header is skipped (round-3 fix
        # of the advisor's round-1 finding).
        for fname in self._files[1:]:
            cols = header_of(fname)
            if cols != first_header:
                raise ValueError(
                    f"CSV header mismatch: {fname} has columns {cols}, "
                    f"but {self._files[0]} has {first_header}"
                )
        self._columns = list(columns) if columns is not None else first_header

    @property
    def metadata(self) -> Dict:
        return {"columns": self._columns, "delimiter": self._delimiter}


class FixedLenBinDataReader(AbstractDataReader):
    """Fixed-width binary records (e.g. .cbin Criteo shards written by
    parsing.convert_criteo_tsv). Shard = file; record i lives at byte
    i*record_bytes — no index to build or load, seeks are pure arithmetic,
    and `read_block` hands whole spans to blob-accepting parsers as one
    contiguous read (the memcpy-speed half of the binary fast path)."""

    # stateless: every read_block opens its own handle
    THREAD_SAFE_SPANS = True

    def __init__(self, path: str, record_bytes: int, suffix: str = ".cbin", **_):
        if record_bytes <= 0:
            raise ValueError("record_bytes must be positive")
        self._rb = int(record_bytes)
        # dir/glob listings filter to `suffix`: a stray _SUCCESS marker or
        # README in the shard directory must neither fail construction nor
        # (worse, if its size divides record_bytes) be reinterpreted as
        # training records; an explicit single-file path is taken verbatim
        self._files = resolve_files(path, require_suffix=suffix)
        if not self._files:
            raise FileNotFoundError(
                f"no input files match {path!r} (suffix {suffix!r})"
            )
        for f in self._files:
            if os.path.getsize(f) % self._rb:
                raise ValueError(
                    f"{f}: size {os.path.getsize(f)} not a multiple of "
                    f"record_bytes={self._rb}"
                )

    @property
    def metadata(self) -> Dict:
        return {"record_bytes": self._rb}

    def create_shards(self) -> List[Shard]:
        return [(f, 0, os.path.getsize(f) // self._rb) for f in self._files]

    def read_block(self, shard_name: str, start: int, end: int) -> bytes:
        with open(shard_name, "rb") as f:
            f.seek(start * self._rb)
            return f.read((end - start) * self._rb)

    def read_span(self, shard_name: str, start: int, end: int) -> List[bytes]:
        blob = self.read_block(shard_name, start, end)
        return [blob[i: i + self._rb] for i in range(0, len(blob), self._rb)]

    def read_records(self, shard_name: str, start: int, end: int) -> Iterator[bytes]:
        yield from self.read_span(shard_name, start, end)


class ODPSDataReader(AbstractDataReader):
    """ODPS/MaxCompute table reader (reference parity: ODPSDataReader —
    table slices as shards, credentials from the environment).

    Needs the `pyodps` package (`odps`), not installed in this sandbox, so
    construction raises a clear error unless it's importable. Auth comes from
    env like the reference: ODPS_PROJECT_NAME / ODPS_ACCESS_ID /
    ODPS_ACCESS_KEY / ODPS_ENDPOINT. Records are yielded as the reader's row
    tuples encoded CSV-style, keeping the dataset_fn contract byte-oriented.

    Verification status: exercised only against a MOCKED pyodps
    (tests/test_data.py) — this sandbox has no MaxCompute credentials, so
    the reader has never run against a live table. The mock mirrors the
    open_reader/tunnel API surface, but treat the first real-table run as
    unproven territory and validate row counts before trusting a job.
    """

    ENV_VARS = (
        "ODPS_PROJECT_NAME", "ODPS_ACCESS_ID", "ODPS_ACCESS_KEY", "ODPS_ENDPOINT"
    )

    def __init__(
        self,
        table: str,
        columns: Optional[List[str]] = None,
        records_per_shard: int = 10000,
        partition: Optional[str] = None,
        **_,
    ):
        try:
            import odps  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "ODPSDataReader needs the pyodps package (`pip install pyodps`); "
                "it is not available in this environment"
            ) from e
        missing = [v for v in self.ENV_VARS if not os.environ.get(v)]
        if missing:
            raise ValueError(f"ODPS credentials missing from env: {missing}")
        from odps import ODPS

        self._odps = ODPS(
            os.environ["ODPS_ACCESS_ID"],
            os.environ["ODPS_ACCESS_KEY"],
            project=os.environ["ODPS_PROJECT_NAME"],
            endpoint=os.environ["ODPS_ENDPOINT"],
        )
        self._table = self._odps.get_table(table)
        self._partition = partition
        self._columns = columns
        self._per_shard = int(records_per_shard)

    def _count(self) -> int:
        with self._table.open_reader(partition=self._partition) as r:
            return r.count

    def create_shards(self) -> List[Shard]:
        n = self._count()
        return [
            (self._table.name, s, min(s + self._per_shard, n))
            for s in range(0, n, self._per_shard)
        ]

    @property
    def metadata(self) -> Dict:
        cols = self._columns or [c.name for c in self._table.table_schema.columns]
        return {"columns": cols, "table": self._table.name}

    def read_records(self, shard_name: str, start: int, end: int) -> Iterator[bytes]:
        import csv
        import io

        with self._table.open_reader(partition=self._partition) as r:
            for row in r[start:end]:
                values = (
                    [row[c] for c in self._columns] if self._columns else list(row.values)
                )
                # proper CSV quoting: string fields may contain the delimiter
                buf = io.StringIO()
                csv.writer(buf, lineterminator="").writerow(
                    ["" if v is None else str(v) for v in values]
                )
                yield buf.getvalue().encode()


class SyntheticDataReader(AbstractDataReader):
    """Deterministic synthetic records for the parity workloads.

    kind: mnist | cifar10 | imagenet224 | criteo | census
    Record formats match the corresponding model_zoo dataset_fn parsers, and
    generation is pure f(record_index), so any worker reading any span gets
    identical bytes — which makes exactly-once accounting testable.
    """

    # pure f(record_index): no shared mutable state across reads
    THREAD_SAFE_SPANS = True

    def __init__(
        self,
        kind: str = "mnist",
        num_records: int = 60000,
        num_shards: int = 4,
        seed: int = 1234,
        vocab: int = 256,
        seq_len: int = 128,
        **_,
    ):
        self._kind = kind
        self._n = int(num_records)
        self._num_shards = max(1, int(num_shards))
        self._seed = seed
        self._vocab = int(vocab)
        self._seq_len = int(seq_len)

    def create_shards(self) -> List[Shard]:
        per = (self._n + self._num_shards - 1) // self._num_shards
        return [
            (f"synthetic-{self._kind}-{i}", i * per, min((i + 1) * per, self._n))
            for i in range(self._num_shards)
            if i * per < self._n
        ]

    @property
    def metadata(self) -> Dict:
        return {
            "kind": self._kind, "num_records": self._n,
            "vocab": self._vocab, "seq_len": self._seq_len,
        }

    def _record(self, idx: int) -> bytes:
        rng = np.random.RandomState((self._seed + idx) % (2**31))
        if self._kind == "mnist":
            label = idx % 10
            img = (rng.rand(784) * 25 + label * 23).astype(np.uint8)
            return bytes([label]) + img.tobytes()
        if self._kind == "cifar10":
            label = idx % 10
            img = (rng.rand(32 * 32 * 3) * 25 + label * 23).astype(np.uint8)
            return bytes([label]) + img.tobytes()
        if self._kind == "imagenet224":
            label = idx % 1000
            img = (rng.rand(64) * 255).astype(np.uint8)  # seed block; parser tiles
            return int(label).to_bytes(2, "little") + img.tobytes()
        if self._kind == "criteo":
            label = rng.randint(0, 2)
            dense = rng.randint(0, 100, 13) + label * 40
            cats = rng.randint(0, 1 << 20, 26) + label
            return (
                str(label)
                + "\t" + "\t".join(str(d) for d in dense)
                + "\t" + "\t".join(format(c, "x") for c in cats)
            ).encode()
        if self._kind == "lm":
            # Learnable token sequences: mostly-deterministic affine bigram
            # process t[i+1] = (5*t[i] + 3) % vocab with 10% noise tokens.
            # vocab/seq_len come from reader params (metadata carries them).
            vocab = self._vocab
            T = self._seq_len
            toks = np.empty(T + 1, np.uint16)
            toks[0] = rng.randint(0, vocab)
            noise = rng.rand(T) < 0.1
            rand_toks = rng.randint(0, vocab, T)
            for t in range(T):
                toks[t + 1] = rand_toks[t] if noise[t] else (5 * int(toks[t]) + 3) % vocab
            return toks.tobytes()
        if self._kind == "census":
            label = rng.randint(0, 2)
            age = 25 + label * 15 + rng.randint(0, 10)
            occ = f"occ{rng.randint(0, 10) + label * 3}"
            row = (
                f"{age}, Private, 1, Bachelors, {8 + label * 4}, Married, {occ}, "
                f"Husband, White, Male, {label * 4000}, 0, {35 + label * 10}, "
                f"United-States, {'>50K' if label else '<=50K'}"
            )
            return row.encode()
        raise ValueError(f"unknown synthetic kind {self._kind!r}")

    def read_records(self, shard_name: str, start: int, end: int) -> Iterator[bytes]:
        for i in range(start, min(end, self._n)):
            yield self._record(i)


def create_data_reader(
    data_path: str, reader_name: str = "", **params
) -> AbstractDataReader:
    """Factory (reference parity: create_data_reader). `synthetic://kind?n=N`
    and plain paths are recognized; reader_name overrides inference."""
    if data_path.startswith("synthetic://"):
        rest = data_path[len("synthetic://"):]
        kind, _, qs = rest.partition("?")
        opts = dict(p.split("=", 1) for p in qs.split("&") if "=" in p)
        aliases = {"seq": "seq_len"}  # the zoo docs use the short form
        extra = {
            aliases.get(k, k): int(float(v))
            for k, v in opts.items() if k not in ("n", "shards")
        }
        return SyntheticDataReader(
            kind=kind or "mnist",
            # int(float(...)) so scientific notation ("n=1e6") works
            num_records=int(float(opts.get("n", params.pop("num_records", 60000)))),
            num_shards=int(float(opts.get("shards", params.pop("num_shards", 4)))),
            **{**params, **extra},
        )
    if data_path.startswith("odps://"):
        # odps://<table>[#partition] — project comes from env, like the
        # reference's client-side table addressing
        rest = data_path[len("odps://"):]
        table, _, part = rest.partition("#")
        return ODPSDataReader(table, partition=part or None, **params)
    if not reader_name:
        def _has(ext):
            return data_path.endswith(ext) or (
                os.path.isdir(data_path)
                and any(f.endswith(ext) for f in os.listdir(data_path))
            )
        # .csv paths stay on textline: only an explicit reader_name="csv"
        # implies a header row to skip
        reader_name = (
            "recordio" if _has(".rio")
            else "criteo_bin" if _has(".cbin")
            else "textline"
        )
    name = reader_name
    if name in ("textline", "tsv"):
        return TextLineDataReader(data_path, **params)
    if name == "csv":
        return CSVDataReader(data_path, **params)
    if name in ("bin", "fixed_bin"):
        return FixedLenBinDataReader(data_path, **params)
    if name == "criteo_bin":
        from elasticdl_tpu.data import parsing

        params.setdefault(
            "record_bytes",
            parsing.criteo_bin_record_bytes(
                int(params.pop("num_dense", 13)), int(params.pop("num_cat", 26))
            ),
        )
        return FixedLenBinDataReader(data_path, **params)
    if name == "odps":
        return ODPSDataReader(data_path, **params)
    if name == "recordio":
        from elasticdl_tpu.data.recordio import RecordIODataReader

        return RecordIODataReader(data_path, **params)
    raise ValueError(f"unknown data reader {name!r}")
