"""Build-and-load for the in-repo C++ data-plane libraries.

Reference parity: the reference shipped its native record machinery as the
external `pyrecordio` C++ package (SURVEY §2.7 item 3); the rebuild keeps the
native code in-tree as single-translation-unit libraries that auto-build with
g++ on first use (a few hundred ms, no deps), with pure-Python twins when no
toolchain is present.

Shared by data/recordio.py (libedlrecordio.so, explicit path) and
data/parsing.py (load_shared("batch_parse") -> libbatch_parse.so): one lock,
one failure memo per library, atomic temp-then-rename so concurrent
master/worker processes never dlopen a half-written .so.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Dict, Optional

from elasticdl_tpu.common.log_utils import default_logger

logger = default_logger(__name__)

NATIVE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "native")

_lock = threading.Lock()
_build_failed: Dict[str, bool] = {}


def build_shared(src: str, lib_path: str, force: bool = False) -> Optional[str]:
    """Compile `src` into `lib_path` with g++ if missing/stale. Returns the
    library path, or None when no usable library can be produced. A failed
    build is remembered per-library so N opens don't pay N compiles."""
    with _lock:
        have_lib = os.path.exists(lib_path)
        if have_lib and not force:
            # A shipped .so without source (or newer than it) is used as-is.
            try:
                fresh = os.path.getmtime(lib_path) >= os.path.getmtime(src)
            except OSError:
                fresh = True
            if fresh:
                return lib_path
        if _build_failed.get(lib_path) and not force:
            return lib_path if have_lib else None
        tmp = f"{lib_path}.{os.getpid()}.tmp"
        try:
            # the module lock EXISTS to serialize this one-time compile
            # (N concurrent opens must pay one build, not N):
            # edl-lint: disable=EDL103
            subprocess.run(
                ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", src, "-o", tmp],
                check=True,
                capture_output=True,
                timeout=120,
            )
            os.replace(tmp, lib_path)
            logger.info("built native library: %s", lib_path)
            _build_failed[lib_path] = False
            return lib_path
        except (subprocess.SubprocessError, FileNotFoundError, OSError) as e:
            _build_failed[lib_path] = True
            if have_lib:
                # Stale-but-loadable beats the pure-Python fallback.
                logger.warning(
                    "native rebuild failed (%s); using existing %s", e, lib_path
                )
                return lib_path
            logger.warning("native build failed for %s (%s); pure-python path", src, e)
            return None
        finally:
            if os.path.exists(tmp):
                try:
                    os.remove(tmp)
                except OSError:
                    pass


def load_shared(name: str, force_build: bool = False) -> Optional[ctypes.CDLL]:
    """Build (if needed) and dlopen native/<name>.cc -> native/lib<name>.so."""
    src = os.path.join(NATIVE_DIR, f"{name}.cc")
    lib_path = os.path.join(NATIVE_DIR, f"lib{name}.so")
    path = build_shared(src, lib_path, force=force_build)
    if path is None:
        return None
    try:
        return ctypes.CDLL(path)
    except OSError as e:
        logger.warning("dlopen(%s) failed: %s", path, e)
        return None
