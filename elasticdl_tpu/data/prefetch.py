"""Async host→device batch prefetching with optional wire compression.

Reference parity: the reference's input path was `tf.data` with internal
prefetching; the rebuild's TaskDataService yields host numpy batches, and on
TPU a synchronous `device_put` per step serializes the host→device transfer
with the compute. Measured on this sandbox's v5e chip (DeepFM, batch 8192,
160B/sample): ~5.6M samples/s with blocking per-step transfers, ~6.2M with
lookahead, against a ~6.5M pure-transfer ceiling — the link, not the math,
bounds the step. A threaded producer measured *slower* (4.9M) than the
main-thread lookahead: `device_put` dispatch contends on the GIL with the
step dispatch, so this implementation keeps everything on the calling thread
and relies on JAX's async dispatch — `device_put` returns before the copy
completes, letting up to `depth` transfers ride behind the running step.

Wire compression (`cast="bfloat16"`): float32/float64 leaves are cast to
bfloat16 on the host before transfer, halving float bytes on the wire. When
the model's compute dtype is bfloat16 (the TPU default here), the values are
cast there anyway, so the computation sees identical inputs.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Iterable, Iterator

import numpy as np

from elasticdl_tpu.common.log_utils import default_logger
from elasticdl_tpu.parallel import mesh as mesh_lib

logger = default_logger(__name__)


def _wire_cast(batch: Any, cast: str) -> Any:
    if not cast:
        return batch
    import jax
    import ml_dtypes

    wire = np.dtype(ml_dtypes.bfloat16) if cast == "bfloat16" else np.dtype(cast)

    def conv(x):
        if isinstance(x, np.ndarray) and x.dtype in (np.float32, np.float64):
            return x.astype(wire)
        return x

    # "mask" stays float32: the worker SUMS it for record accounting, and
    # bf16 addition is exact only up to 256 — a cast mask would corrupt
    # records_done and with it the exactly-once protocol.
    out = dict(batch)
    for k, v in out.items():
        if k == "mask":
            continue
        out[k] = jax.tree_util.tree_map(conv, v)
    return out


def prefetch_to_device(
    mesh, batches: Iterable[Any], depth: int = 2, cast: str = "",
    partition=None,
) -> Iterator[Any]:
    """Yield device-resident (batch-sharded) batches, keeping up to `depth`
    transfers in flight ahead of the consumer. depth<=0 disables lookahead
    but still device-puts (and wire-casts) each batch."""
    it = iter(batches)

    def put(host_batch):
        return mesh_lib.shard_batch(mesh, _wire_cast(host_batch, cast), partition)

    if depth <= 0:
        for b in it:
            yield put(b)
        return

    buf: deque = deque()
    exhausted = False
    while not exhausted and len(buf) < depth:
        try:
            buf.append(put(next(it)))
        except StopIteration:
            exhausted = True
    while buf:
        cur = buf.popleft()
        if not exhausted:
            try:
                buf.append(put(next(it)))
            except StopIteration:
                exhausted = True
        yield cur
