"""Async host→device batch prefetching with optional wire compression.

Reference parity: the reference's input path was `tf.data` with internal
prefetching; the rebuild's TaskDataService yields host numpy batches, and on
TPU a synchronous `device_put` per step serializes the host→device transfer
with the compute. Measured on this sandbox's v5e chip (DeepFM, batch 8192,
160B/sample): ~5.6M samples/s with blocking per-step transfers, ~6.2M with
lookahead, against a ~6.5M pure-transfer ceiling — the link, not the math,
bounds the step. A threaded producer measured *slower* (4.9M) than the
main-thread lookahead: `device_put` dispatch contends on the GIL with the
step dispatch, so this implementation keeps everything on the calling thread
and relies on JAX's async dispatch — `device_put` returns before the copy
completes, letting up to `depth` transfers ride behind the running step.

Wire compression (`cast="bfloat16"`): float32/float64 leaves are cast to
bfloat16 on the host before transfer, halving float bytes on the wire. When
the model's compute dtype is bfloat16 (the TPU default here), the values are
cast there anyway, so the computation sees identical inputs.

Elasticity (rescale fast path): in-flight device batches carry the OLD
mesh's shardings across a re-formation, so the prefetcher keeps each
pending batch's HOST copy alongside the device copy and exposes `drain()`
— the worker calls it on reform/rescale, gets the pending host batches
back, and requeues them through the new mesh instead of silently dropping
them (exactly-once accounting is span-based, so a dropped-but-uncounted
batch would be re-read anyway after a full teardown — but an IN-PLACE
rescale has no teardown, and without the drain those records would be
lost from the task's span).

`depth` and `cast` resolve from the environment when not given:
`EDL_PREFETCH_DEPTH` (default 2) and `EDL_PREFETCH_CAST` (default "") —
so deployments can tune the lookahead window without a config/argv change.
"""

from __future__ import annotations

import os
from collections import deque
from typing import Any, Iterable, Iterator, List, Optional

import numpy as np

import time

from elasticdl_tpu.common.log_utils import default_logger
from elasticdl_tpu.observability import profile as profile_lib
from elasticdl_tpu.observability import tracing
from elasticdl_tpu.observability.registry import default_registry
from elasticdl_tpu.parallel import mesh as mesh_lib

logger = default_logger(__name__)

DEFAULT_DEPTH = 2

# prefetch telemetry: batch flow + drain accounting (a drain is the
# rescale-path event — its batch count is how much lookahead a resize
# had to requeue). The depth gauge tracks the most recent prefetcher's
# configured lookahead (one live prefetcher per worker in practice).
_reg = default_registry()
_PF_BATCHES = _reg.counter(
    "edl_prefetch_batches_total", "device batches served to the step loop")
_PF_DRAINS = _reg.counter(
    "edl_prefetch_drains_total", "drain() calls (reform/rescale requeues)")
_PF_DRAINED_BATCHES = _reg.counter(
    "edl_prefetch_drained_batches_total",
    "pending host batches handed back by drains")
_PF_DEPTH = _reg.gauge(
    "edl_prefetch_depth", "configured lookahead of the latest prefetcher")


def resolve_depth(depth: Optional[int]) -> int:
    """None -> EDL_PREFETCH_DEPTH -> default; explicit values win."""
    if depth is not None:
        return int(depth)
    try:
        return int(os.environ.get("EDL_PREFETCH_DEPTH", DEFAULT_DEPTH))
    except ValueError:
        return DEFAULT_DEPTH


def resolve_cast(cast: Optional[str]) -> str:
    """None -> EDL_PREFETCH_CAST -> no cast; explicit values win."""
    if cast is not None:
        return cast
    return os.environ.get("EDL_PREFETCH_CAST", "")


def _wire_cast(batch: Any, cast: str) -> Any:
    if not cast:
        return batch
    import jax
    import ml_dtypes

    wire = np.dtype(ml_dtypes.bfloat16) if cast == "bfloat16" else np.dtype(cast)

    def conv(x):
        if isinstance(x, np.ndarray) and x.dtype in (np.float32, np.float64):
            return x.astype(wire)
        return x

    # "mask" stays float32: the worker SUMS it for record accounting, and
    # bf16 addition is exact only up to 256 — a cast mask would corrupt
    # records_done and with it the exactly-once protocol.
    out = dict(batch)
    for k, v in out.items():
        if k == "mask":
            continue
        out[k] = jax.tree_util.tree_map(conv, v)
    return out


class DevicePrefetcher:
    """Iterator of device-resident (batch-sharded) batches keeping up to
    `depth` transfers in flight ahead of the consumer, with an explicit
    `drain()` for elastic re-formation. depth<=0 disables lookahead but
    still device-puts (and wire-casts) each batch.

    Each pending slot holds (host_batch, device_batch): the host copy costs
    no extra materialization (the source yields host batches anyway) and is
    what `drain()` hands back for requeueing — the device copies are
    dropped, since their shardings die with the old mesh.
    """

    def __init__(
        self,
        mesh,
        batches: Iterable[Any],
        depth: Optional[int] = None,
        cast: Optional[str] = None,
        partition=None,
    ):
        self._mesh = mesh
        self.source: Iterator[Any] = iter(batches)
        self.depth = resolve_depth(depth)
        self.cast = resolve_cast(cast)
        self._partition = partition
        self._buf: deque = deque()   # (host_batch, device_batch)
        self._exhausted = False
        self._drained = False
        _PF_DEPTH.set(self.depth)

    def _put(self, host_batch):
        # h2d attribution (observability/profile.py): the cast + sharded
        # device_put dispatch is the transfer half of the input path. The
        # profiler add is two perf_counter reads + a float add — cheap
        # enough for the always-on contract.
        t0 = time.perf_counter()
        try:
            return mesh_lib.shard_batch(
                self._mesh, _wire_cast(host_batch, self.cast), self._partition
            )
        finally:
            profile_lib.get_profiler().add(
                "h2d", time.perf_counter() - t0
            )

    def _fill(self) -> None:
        prof = profile_lib.get_profiler()
        while not self._exhausted and len(self._buf) < max(1, self.depth):
            t0 = time.perf_counter()
            try:
                host = next(self.source)
            except StopIteration:
                self._exhausted = True
                return
            finally:
                # blocking on the reader/parse pipeline IS the data wait
                prof.add("data_wait", time.perf_counter() - t0)
            self._buf.append((host, self._put(host)))

    def __iter__(self) -> "DevicePrefetcher":
        return self

    def __next__(self):
        if self._drained:
            raise StopIteration
        if self.depth <= 0:
            t0 = time.perf_counter()
            host = next(self.source)
            profile_lib.get_profiler().add(
                "data_wait", time.perf_counter() - t0
            )
            _PF_BATCHES.inc()
            return self._put(host)
        self._fill()
        if not self._buf:
            raise StopIteration
        _, device_batch = self._buf.popleft()
        _PF_BATCHES.inc()
        return device_batch

    def drain(self) -> List[Any]:
        """Invalidate the lookahead window: return the pending HOST batches
        (oldest first) and stop this prefetcher. The caller requeues them —
        through a new prefetcher on the new mesh, or back to the task
        service — so no record silently disappears across a re-formation.
        The un-consumed source remains available as `self.source`."""
        with tracing.span("prefetch.drain") as sp:
            pending = [host for host, _ in self._buf]
            self._buf.clear()
            self._drained = True
            sp.set(pending_batches=len(pending))
        _PF_DRAINS.inc()
        _PF_DRAINED_BATCHES.inc(len(pending))
        return pending

    def close(self) -> None:
        """Release the source (generator-based sources stop cleanly)."""
        self._buf.clear()
        self._drained = True
        close = getattr(self.source, "close", None)
        if close is not None:
            close()


def prefetch_to_device(
    mesh, batches: Iterable[Any], depth: Optional[int] = None,
    cast: Optional[str] = None, partition=None,
) -> DevicePrefetcher:
    """Yield device-resident (batch-sharded) batches, keeping up to `depth`
    transfers in flight ahead of the consumer (see DevicePrefetcher; this
    wrapper is the stable entry point call sites use)."""
    return DevicePrefetcher(
        mesh, batches, depth=depth, cast=cast, partition=partition
    )
