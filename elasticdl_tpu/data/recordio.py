"""RecordIO-style sharded record files: Python API over the native reader.

Reference parity: the reference reads training data from RecordIO shards via
the external C++ `pyrecordio` package, and tasks are (file, offset, count)
spans (SURVEY §2.4). This module provides the same role for the EDLR format
(see native/recordio.cc for the layout): a ctypes binding to the C++
reader/writer plus a pure-Python twin used when the native library isn't
built (and to cross-check it in tests).

The native library auto-builds on first use when a toolchain is present
(g++, one translation unit, no deps — a few hundred ms).
"""

from __future__ import annotations

import collections
import ctypes
import glob
import os
import struct
import zlib
from typing import Dict, Iterator, List, Optional, Tuple

from elasticdl_tpu.common.log_utils import default_logger
from elasticdl_tpu.data import nativelib
from elasticdl_tpu.data.reader import AbstractDataReader, Shard

logger = default_logger(__name__)

_NATIVE_DIR = nativelib.NATIVE_DIR
_LIB_PATH = os.path.join(_NATIVE_DIR, "libedlrecordio.so")
_lib: Optional[ctypes.CDLL] = None
_FILE_MAGIC = b"EDLR"
_CHUNK_MAGIC = b"CHNK"
_INDEX_MAGIC = b"INDX"
_VERSION = 1


def build_native(force: bool = False) -> Optional[str]:
    """Compile libedlrecordio.so with g++ if missing (delegates to the shared
    builder in data/nativelib.py). Returns path or None."""
    src = os.path.join(_NATIVE_DIR, "recordio.cc")
    return nativelib.build_shared(src, _LIB_PATH, force=force)


def _load_lib() -> Optional[ctypes.CDLL]:
    global _lib
    if _lib is not None:
        return _lib
    path = build_native()  # fast no-op when the .so is present and fresh
    if path is None:
        return None
    lib = ctypes.CDLL(path)
    lib.edlr_reader_open.restype = ctypes.c_void_p
    lib.edlr_reader_open.argtypes = [ctypes.c_char_p]
    lib.edlr_reader_num_records.restype = ctypes.c_longlong
    lib.edlr_reader_num_records.argtypes = [ctypes.c_void_p]
    lib.edlr_reader_read.restype = ctypes.c_longlong
    lib.edlr_reader_read.argtypes = [ctypes.c_void_p, ctypes.c_longlong, ctypes.c_longlong]
    lib.edlr_reader_buffer.restype = ctypes.POINTER(ctypes.c_uint8)
    lib.edlr_reader_buffer.argtypes = [ctypes.c_void_p]
    lib.edlr_reader_error.restype = ctypes.c_char_p
    lib.edlr_reader_error.argtypes = [ctypes.c_void_p]
    lib.edlr_reader_close.restype = None
    lib.edlr_reader_close.argtypes = [ctypes.c_void_p]
    lib.edlr_writer_open.restype = ctypes.c_void_p
    lib.edlr_writer_open.argtypes = [ctypes.c_char_p, ctypes.c_longlong]
    lib.edlr_writer_write.restype = ctypes.c_int
    lib.edlr_writer_write.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_longlong]
    lib.edlr_writer_close.restype = ctypes.c_longlong
    lib.edlr_writer_close.argtypes = [ctypes.c_void_p]
    _lib = lib
    return _lib


# --------------------------------------------------------------------- #
# Writers


class RecordIOWriter:
    """Writes one EDLR shard file (native when available)."""

    def __init__(self, path: str, chunk_bytes: int = 1 << 20,
                 prefer_native: bool = True):
        self._path = path
        self._native = _load_lib() if prefer_native else None
        self.num_records = 0
        self._closed = False
        if self._native is not None:
            self._h = self._native.edlr_writer_open(path.encode(), chunk_bytes)
            if not self._h:
                raise IOError(f"cannot open {path} for writing")
        else:
            self._f = open(path, "wb")
            self._f.write(_FILE_MAGIC + struct.pack("<I", _VERSION))
            self._chunk_bytes = chunk_bytes
            self._payload = bytearray()
            self._chunk_records = 0
            self._index: List[Tuple[int, int]] = []

    def write(self, record: bytes) -> None:
        self.num_records += 1
        if self._native is not None:
            if self._native.edlr_writer_write(self._h, record, len(record)) != 0:
                raise IOError("native write failed")
            return
        self._payload += struct.pack("<I", len(record)) + record
        self._chunk_records += 1
        if len(self._payload) >= self._chunk_bytes:
            self._flush_chunk()

    def _flush_chunk(self) -> None:
        if not self._chunk_records:
            return
        self._index.append((self._f.tell(), self.num_records - self._chunk_records))
        crc = zlib.crc32(bytes(self._payload)) & 0xFFFFFFFF
        self._f.write(
            _CHUNK_MAGIC
            + struct.pack("<IQI", self._chunk_records, len(self._payload), crc)
        )
        self._f.write(self._payload)
        self._payload = bytearray()
        self._chunk_records = 0

    def close(self) -> int:
        if self._closed:
            return self.num_records
        self._closed = True
        if self._native is not None:
            n = self._native.edlr_writer_close(self._h)
            self._h = None
            if n < 0:
                raise IOError("native close failed")
            return int(n)
        self._flush_chunk()
        index_off = self._f.tell()
        self._f.write(_INDEX_MAGIC + struct.pack("<I", len(self._index)))
        for off, first in self._index:
            self._f.write(struct.pack("<QQ", off, first))
        self._f.write(struct.pack("<Q", index_off) + _FILE_MAGIC)
        self._f.close()
        return self.num_records

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# --------------------------------------------------------------------- #
# Readers


class _PyShardReader:
    """Pure-Python EDLR reader (format twin of the native one)."""

    def __init__(self, path: str):
        self._f = open(path, "rb")
        head = self._f.read(8)
        if head[:4] != _FILE_MAGIC or struct.unpack("<I", head[4:])[0] != _VERSION:
            raise IOError(f"{path}: not an EDLR file")
        self._f.seek(-12, os.SEEK_END)
        index_off, magic = struct.unpack("<Q4s", self._f.read(12))
        if magic != _FILE_MAGIC:
            raise IOError(f"{path}: bad footer")
        self._f.seek(index_off)
        imagic, num_chunks = struct.unpack("<4sI", self._f.read(8))
        if imagic != _INDEX_MAGIC:
            raise IOError(f"{path}: bad index")
        self._index = [
            struct.unpack("<QQ", self._f.read(16)) for _ in range(num_chunks)
        ]
        if self._index:
            self._f.seek(self._index[-1][0] + 4)
            (n,) = struct.unpack("<I", self._f.read(4))
            self.num_records = self._index[-1][1] + n
        else:
            self.num_records = 0

    def read(self, start: int, end: int) -> Iterator[bytes]:
        end = min(end, self.num_records)
        if start >= end:
            return
        lo = 0
        for i, (_, first) in enumerate(self._index):
            if first <= start:
                lo = i
            else:
                break
        for ci in range(lo, len(self._index)):
            off, first = self._index[ci]
            if first >= end:
                break
            self._f.seek(off)
            magic, n, payload_len, crc = struct.unpack("<4sIQI", self._f.read(20))
            if magic != _CHUNK_MAGIC:
                raise IOError("bad chunk magic")
            payload = self._f.read(payload_len)
            if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
                raise IOError("chunk crc mismatch")
            pos = 0
            for k in range(n):
                (length,) = struct.unpack_from("<I", payload, pos)
                pos += 4
                rec = payload[pos : pos + length]
                pos += length
                gid = first + k
                if start <= gid < end:
                    yield bytes(rec)

    def close(self):
        self._f.close()


class _NativeShardReader:
    def __init__(self, path: str, lib: ctypes.CDLL):
        self._lib = lib
        self._h = lib.edlr_reader_open(path.encode())
        if not self._h:
            raise IOError(f"{path}: not a readable EDLR file")
        self.num_records = int(lib.edlr_reader_num_records(self._h))

    def read(self, start: int, end: int) -> Iterator[bytes]:
        n = self._lib.edlr_reader_read(self._h, start, end)
        if n < 0:
            raise IOError(
                f"native read failed: {self._lib.edlr_reader_error(self._h).decode()}"
            )
        buf = ctypes.string_at(self._lib.edlr_reader_buffer(self._h), n)
        pos = 0
        while pos < n:
            (length,) = struct.unpack_from("<I", buf, pos)
            pos += 4
            yield buf[pos : pos + length]
            pos += length

    def close(self):
        if self._h:
            self._lib.edlr_reader_close(self._h)
            self._h = None

    def __del__(self):
        self.close()


def open_shard(path: str, prefer_native: bool = True):
    lib = _load_lib() if prefer_native else None
    if lib is not None:
        return _NativeShardReader(path, lib)
    return _PyShardReader(path)


class RecordIODataReader(AbstractDataReader):
    """AbstractDataReader over a directory/glob of EDLR shard files."""

    def __init__(self, path: str, prefer_native: bool = True, **_):
        if any(c in path for c in "*?["):
            self._files = sorted(glob.glob(path))
        elif os.path.isdir(path):
            self._files = sorted(
                os.path.join(path, f)
                for f in os.listdir(path)
                if f.endswith(".rio")
            )
        else:
            self._files = [path] if os.path.exists(path) else []
        if not self._files:
            raise FileNotFoundError(f"no recordio files match {path!r}")
        self._prefer_native = prefer_native
        # Workers stream one shard at a time; a small LRU bounds open fds (a
        # master over thousands of shards would otherwise exhaust the ulimit)
        # and chunk-cache memory. Readers backing a partially-consumed
        # read_records() generator are pinned (refcounted) so eviction never
        # closes a file mid-iteration; pinned entries may transiently push the
        # cache past its bound.
        self._readers: "collections.OrderedDict[str, object]" = (
            collections.OrderedDict()
        )
        self._pins: Dict[str, int] = {}
        self._max_open = 8

    def _reader(self, fname: str):
        if fname in self._readers:
            self._readers.move_to_end(fname)
            return self._readers[fname]
        reader = open_shard(fname, self._prefer_native)
        self._readers[fname] = reader
        evictable = [f for f in self._readers if not self._pins.get(f)]
        while len(self._readers) > self._max_open and evictable:
            old_name = evictable.pop(0)
            if old_name == fname:
                continue
            self._readers.pop(old_name).close()
        return reader

    def _pin(self, fname: str) -> None:
        self._pins[fname] = self._pins.get(fname, 0) + 1

    def _unpin(self, fname: str) -> None:
        n = self._pins.get(fname, 0) - 1
        if n <= 0:
            self._pins.pop(fname, None)
        else:
            self._pins[fname] = n

    def create_shards(self) -> List[Shard]:
        shards = []
        for f in self._files:
            reader = open_shard(f, self._prefer_native)
            try:
                shards.append((f, 0, reader.num_records))
            finally:
                reader.close()
        return shards

    def read_records(self, shard_name: str, start: int, end: int) -> Iterator[bytes]:
        reader = self._reader(shard_name)
        self._pin(shard_name)
        try:
            yield from reader.read(start, end)
        finally:
            self._unpin(shard_name)
