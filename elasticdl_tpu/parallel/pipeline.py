"""GPipe-style pipeline parallelism over a `pp` mesh axis.

Net-new relative to the reference (william-wang/elasticdl scales only by
data parallelism + the PS tier), completing the rebuild's parallelism
matrix: dp (psum over `data`), tp (GSPMD-partitioned kernels over
`model`), sp (ring/Ulysses over `seq`), and pp (this module).

TPU-first design — the scaling-book pipeline recipe, not a scheduler
thread pool: stage parameters are STACKED with a leading stage dim sharded
`P('pp')`, and the whole schedule runs inside ONE `shard_map` region as a
`lax.scan` over ticks. Each tick every device applies ITS resident stage
to the activation it holds, then the activations rotate one hop along the
ring with `lax.ppermute` — exactly the bounded, ICI-riding collective
pattern ring attention uses. Microbatch m enters stage 0 at tick m and
leaves stage S-1 at tick m+S-1; the scan runs M+S-1 ticks, so the classic
GPipe bubble is (S-1)/(M+S-1) of the ticks. Autodiff flows through
scan+ppermute (the same machinery ring attention differentiates through),
so `jax.grad` of a pipelined forward IS pipelined backprop — no hand
-written backward schedule.

The last stage's outputs are returned replicated via a `psum` over `pp`
(every other shard contributes zeros). That one output-sized collective
keeps the API shape-transparent: `gpipe(...)` is a drop-in for folding x
through the stages sequentially.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from elasticdl_tpu.common import jax_compat

jax_compat.ensure()  # older-jax API adapters (no-op on current jax)
from jax import lax
from jax.sharding import PartitionSpec as P

from elasticdl_tpu.common.constants import MeshAxis

PIPE_AXIS = MeshAxis.PIPE


def stage_partition_specs(stage_params: Any, axis: str = PIPE_AXIS) -> Any:
    """P(axis, None, ...) for every leaf of a stacked stage-param tree."""
    return jax.tree_util.tree_map(
        lambda leaf: P(axis, *([None] * (leaf.ndim - 1))), stage_params
    )


def gpipe(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stage_params: Any,
    x: jax.Array,
    *,
    num_microbatches: int,
    axis: str = PIPE_AXIS,
) -> jax.Array:
    """Fold `x` through S pipelined stages: equivalent to

        for s in range(S): x = stage_fn(params[s], x)

    but with stage s resident on pp-shard s and microbatches streaming
    through the ring.

    stage_fn: (per-stage params, (mb, ...) activation) -> same-shape
      activation. Must be shape-preserving (homogeneous stages — the
      transformer-block case).
    stage_params: pytree with leading stage dim S on every leaf, sharded
      P(axis) (see stage_partition_specs). S = the mesh's `axis` size.
    x: (B, ...) with B divisible by num_microbatches; replicated over
      `axis` (shard other mesh axes freely — they stay auto).
    """
    mesh = jax.sharding.get_abstract_mesh()
    if axis not in mesh.axis_names:
        # no pp axis: run the stages sequentially (single-chip fallback,
        # mirroring sequence_parallel_attention's no-seq-axis behavior)
        s_total = jax.tree_util.tree_leaves(stage_params)[0].shape[0]
        for s in range(s_total):
            x = stage_fn(
                jax.tree_util.tree_map(lambda l: l[s], stage_params), x)
        return x
    n_stages = mesh.shape[axis]
    s_stacked = jax.tree_util.tree_leaves(stage_params)[0].shape[0]
    if s_stacked != n_stages:
        raise ValueError(
            f"stage_params stack {s_stacked} stages but mesh axis "
            f"{axis!r} has {n_stages} shards — they must match")
    batch = x.shape[0]
    if batch % num_microbatches:
        raise ValueError(
            f"batch {batch} not divisible by num_microbatches "
            f"{num_microbatches}")
    mb = batch // num_microbatches

    def shard_fn(params_local, x_full):
        # params_local leaves: (1, ...) — this device's stage
        params_one = jax.tree_util.tree_map(
            lambda l: jnp.squeeze(l, axis=0), params_local)
        idx = lax.axis_index(axis)
        m_total = num_microbatches
        x_micro = x_full.reshape((m_total, mb) + x_full.shape[1:])
        fwd_perm = [(i, i + 1) for i in range(n_stages - 1)]

        def tick(carry, t):
            act_in, outs = carry
            # stage 0 consumes the incoming stream (clamped index: ticks
            # past the last microbatch feed don't-cares that drain out of
            # the scan window before reaching the last stage)
            x_t = lax.dynamic_index_in_dim(
                x_micro, jnp.clip(t, 0, m_total - 1), axis=0,
                keepdims=False)
            inp = jnp.where(idx == 0, x_t, act_in)
            out = stage_fn(params_one, inp)
            # the LAST stage finished microbatch m = t - (S-1) this tick
            m = t - (n_stages - 1)
            store = (idx == n_stages - 1) & (m >= 0)
            outs = lax.dynamic_update_index_in_dim(
                outs,
                jnp.where(store, out, lax.dynamic_index_in_dim(
                    outs, jnp.clip(m, 0, m_total - 1), axis=0,
                    keepdims=False)),
                jnp.clip(m, 0, m_total - 1), axis=0)
            # rotate activations one hop down the ring; stage 0 receives
            # zeros (unused — it reads the stream)
            act_next = lax.ppermute(out, axis, fwd_perm)
            return (act_next, outs), None

        # carries become pp-varying after the first tick; mark the zero
        # initials varying up front or the scan rejects the type mismatch
        outs0 = lax.pcast(
            jnp.zeros((m_total, mb) + x_full.shape[1:], x_full.dtype),
            (axis,), to="varying")
        act0 = lax.pcast(
            jnp.zeros((mb,) + x_full.shape[1:], x_full.dtype),
            (axis,), to="varying")
        (_, outs), _ = lax.scan(
            tick, (act0, outs0), jnp.arange(m_total + n_stages - 1))
        # only the last shard holds real outputs; psum replicates them
        outs = lax.psum(
            jnp.where(idx == n_stages - 1, outs, 0.0), axis)
        return outs.reshape((batch,) + x_full.shape[1:])

    spec_params = stage_partition_specs(stage_params, axis)
    out = jax.shard_map(
        shard_fn,
        in_specs=(spec_params, P()),
        out_specs=P(),
        axis_names={axis},
    )(stage_params, x)
    return out
