"""Multi-process distributed context: one global mesh across worker
processes, with re-formation as the unit of elastic recovery.

Reference parity: the reference's allreduce mode ran one Horovod ring across
worker pods (NCCL/Gloo), re-built by a master-hosted rendezvous when
membership changed (SURVEY §3.4). The TPU-native rebuild uses
`jax.distributed` + ONE `jax.sharding.Mesh` over every process's devices;
gradient averaging is the `psum` XLA inserts over the `data` axis (ICI
in-slice, DCN across hosts). XLA's world is static per initialize(), so
elasticity = re-formation: tear the world down, re-initialize with the new
process set, restore from the latest checkpoint, resume at the exact task
boundary (the task queue makes this data-loss-free).

A worker cohort (elasticdl_tpu/worker/cohort.py) runs SPMD: every process
executes the same jitted steps; per-process data enters as process-local
shards of the global batch via `make_global_batch`.
"""

from __future__ import annotations

import os
from collections.abc import Mapping
from typing import Any, Dict, Optional, Sequence

import jax
import numpy as np

from elasticdl_tpu.common.log_utils import default_logger
from elasticdl_tpu.observability import tracing
from elasticdl_tpu.observability.registry import default_registry
from elasticdl_tpu.parallel import mesh as mesh_lib

logger = default_logger(__name__)

_reg = default_registry()
_HANDOFF_STAGED = _reg.counter(
    "edl_handoff_staged_leaves_total",
    "state leaves pulled to host because their owner devices vanish")
_HANDOFF_REPLICATED = _reg.counter(
    "edl_handoff_replicated_leaves_total",
    "leaves that lost their spec on the new mesh and fell back to "
    "replication (correct but larger — watch this on shrinks)")


class CohortContext:
    """The per-process handle on the distributed world."""

    def __init__(self, coordinator_addr: str, num_processes: int,
                 process_id: int, world_version: int = 0):
        self.coordinator_addr = coordinator_addr
        self.num_processes = num_processes
        self.process_id = process_id
        self.world_version = world_version
        self._initialized = False

    # ------------------------------------------------------------------ #

    def initialize(self) -> None:
        """jax.distributed.initialize — collective, blocks until every
        process of the world version has joined."""
        jax.distributed.initialize(
            coordinator_address=self.coordinator_addr,
            num_processes=self.num_processes,
            process_id=self.process_id,
        )
        self._initialized = True
        logger.info(
            "distributed world v%d up: process %d/%d, %d global devices",
            self.world_version, self.process_id, self.num_processes,
            len(jax.devices()),
        )

    def shutdown(self) -> None:
        if self._initialized:
            jax.distributed.shutdown()
            self._initialized = False

    @property
    def is_leader(self) -> bool:
        return self.process_id == 0

    # ------------------------------------------------------------------ #

    def global_mesh(self, axis_sizes: Optional[Dict[str, int]] = None):
        """Mesh over ALL processes' devices (default: 1-D data axis)."""
        return mesh_lib.build_mesh(axis_sizes, jax.devices())

    def broadcast_ints(self, values: Sequence[int]) -> np.ndarray:
        """Leader -> all: small int64 control vector (the cohort's task/
        checkpoint/LR protocol rides this).

        Shipped as int32 HALVES: with jax_enable_x64 off (the default,
        everywhere in this repo), an int64 array entering
        broadcast_one_to_all is canonicalized to int32 — silently wrapping
        anything past 2^31 (float64 LR bit-patterns; record spans of a
        Criteo-1TB-sized file). Splitting each value into two int32s keeps
        the full 64 bits across the wire."""
        from jax.experimental import multihost_utils

        arr = np.ascontiguousarray(np.asarray(values, np.int64))
        halves = arr.view(np.int32)            # (2n,), little-endian pairs
        out = np.asarray(
            multihost_utils.broadcast_one_to_all(
                halves, is_source=self.is_leader
            ),
            dtype=np.int32,
        )
        return np.ascontiguousarray(out).view(np.int64)

    def allgather_ints(self, values: Sequence[int]) -> np.ndarray:
        """All -> all: every process contributes a small int64 row, every
        process receives the (num_processes, len(values)) stack — the
        follower->leader telemetry channel (worker/cohort.py's member-
        stats exchange rides this at task boundaries). COLLECTIVE: every
        process of the world must call it with an equal-length row.

        Same int32-halving discipline as broadcast_ints: with
        jax_enable_x64 off an int64 array entering the collective would be
        silently canonicalized to int32, wrapping anything past 2^31."""
        arr = np.ascontiguousarray(np.asarray(values, np.int64))
        if jax.process_count() == 1:
            return arr[None, :]
        from jax.experimental import multihost_utils

        halves = arr.view(np.int32)            # (2n,), little-endian pairs
        out = np.asarray(
            multihost_utils.process_allgather(halves), dtype=np.int32
        )                                      # (P, 2n)
        return np.ascontiguousarray(out).view(np.int64)

    def barrier(self, name: str) -> None:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)


def make_global_batch(mesh, batch: Any, partition=None) -> Any:
    """Assemble a global sharded batch from each process's IDENTICAL host
    batch: every process holds the same full global batch (readers are
    deterministic), so each local device simply pulls its own slice via
    `make_array_from_callback` — correct for ANY partition spec (data, seq,
    or mixed axes across the process boundary), with no cross-process data
    motion.

    Single-process meshes fall through to the ordinary shard_batch path.
    """
    if jax.process_count() == 1:
        return mesh_lib.shard_batch(mesh, batch, partition)

    from jax.sharding import NamedSharding

    def put(x, sharding):
        x = np.asarray(x)
        return jax.make_array_from_callback(
            x.shape, sharding, lambda idx: x[idx]
        )

    if not isinstance(batch, Mapping):
        # non-dict host batch (bare array / tuple pytree): per-key partition
        # overrides can't apply, so the whole tree takes the default batch
        # spec — mirrors shard_batch's partition=None path
        sh = NamedSharding(mesh, mesh_lib.batch_key_spec(mesh, "", partition))
        return jax.tree_util.tree_map(lambda x: put(x, sh), batch)
    out = {}
    for key, value in batch.items():
        sh = NamedSharding(mesh, mesh_lib.batch_key_spec(mesh, key, partition))
        out[key] = jax.tree_util.tree_map(lambda x, s=sh: put(x, s), value)
    return out


def make_global_batch_stack(mesh, batches, partition=None) -> Any:
    """K identical-on-every-process host batches -> one global pytree with
    a leading step axis (leaves (K, B, ...), sharded P(None, <batch spec>))
    for `Trainer.train_many` — the multi-process twin of
    `mesh.shard_batch_stack`, assembled per-device like make_global_batch."""
    if jax.process_count() == 1:
        return mesh_lib.shard_batch_stack(mesh, batches, partition)

    from jax.sharding import NamedSharding, PartitionSpec as P

    def put(leaves, spec):
        x = np.stack([np.asarray(l) for l in leaves])
        sh = NamedSharding(mesh, P(None, *spec))
        return jax.make_array_from_callback(x.shape, sh, lambda idx: x[idx])

    if not isinstance(batches[0], Mapping):
        # non-dict batches: default data spec on every leaf (matches
        # make_global_batch / mesh.shard_batch_stack fallbacks)
        spec = mesh_lib.batch_key_spec(mesh, "", partition)
        return jax.tree_util.tree_map(
            lambda *ls: put(ls, spec), *batches)
    out = {}
    for key in batches[0]:
        spec = mesh_lib.batch_key_spec(mesh, key, partition)
        out[key] = jax.tree_util.tree_map(
            lambda *ls, s=spec: put(ls, s), *(b[key] for b in batches))
    return out


def neighbor_world_sizes(
    current: int,
    pending: Optional[int] = None,
    min_size: int = 1,
    max_size: Optional[int] = None,
) -> list:
    """Candidate next world sizes for speculative compilation: the
    master's announced pending size (first — it is the one about to
    happen), then N-1 and N+1, clamped to [min_size, max_size]."""
    sizes = {current - 1, current + 1}
    if pending is not None:
        sizes.add(int(pending))
    sizes = {
        s for s in sizes
        if s >= min_size and (max_size is None or s <= max_size)
        and s != current
    }
    return sorted(sizes, key=lambda s: (s != pending, abs(s - current), s))


# ---------------------------------------------------------------------- #
# Live state handoff (rescale fast path, part 3)
#
# A PLANNED resize does not need the checkpoint-restore round trip: the
# donor arrays are still resident, and jax.device_put reshards them
# directly onto the new mesh. Only shards whose owner set changes move;
# a leaf already laid out identically passes through untouched.


class _HostStaged:
    """A state leaf pulled to host because its owner devices are about to
    disappear (cross-process teardown path); carries the PartitionSpec it
    had so `apply` can lay it back out on the new mesh."""

    __slots__ = ("array", "spec")

    def __init__(self, array, spec):
        self.array = array
        self.spec = spec


def _leaf_spec(x):
    from jax.sharding import PartitionSpec as P

    sharding = getattr(x, "sharding", None)
    spec = getattr(sharding, "spec", None)
    return spec if spec is not None else P()


def stage_leaf(x, spec=None) -> _HostStaged:
    """Wrap one array for the staged half of a handoff: the embedding
    tier's shard migrations (embedding/reshard.py) ride the same
    stage-then-reshard lane a TrainState leaf takes when its owner
    devices vanish. `spec` defaults to the array's own PartitionSpec
    (P() for host numpy arrays)."""
    import numpy as _np

    if isinstance(x, jax.Array):
        return _HostStaged(_np.asarray(jax.device_get(x)),
                           spec if spec is not None else _leaf_spec(x))
    return _HostStaged(_np.asarray(x), spec if spec is not None else _leaf_spec(x))


def reshard_state(state: Any, new_mesh) -> Any:
    """Reshard a TrainState (or any pytree of jax arrays) onto `new_mesh`,
    preserving each leaf's PartitionSpec (pruned to the new mesh's axes).
    Leaves whose layout is unchanged are untouched; a spec the new mesh
    cannot satisfy (row count not divisible by the shrunken axis) falls
    back to replication with a warning — correct, just larger."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    def move(x):
        if isinstance(x, _HostStaged):
            value, spec = x.array, x.spec
        elif isinstance(x, jax.Array):
            value, spec = x, _leaf_spec(x)
        else:
            return x
        spec = mesh_lib.prune_spec(new_mesh, spec)
        try:
            return jax.device_put(value, NamedSharding(new_mesh, spec))
        except ValueError:
            _HANDOFF_REPLICATED.inc()
            logger.warning(
                "leaf %s cannot keep spec %s on the %s mesh; replicating",
                getattr(value, "shape", "?"), spec,
                dict(zip(new_mesh.axis_names, new_mesh.devices.shape)),
            )
            return jax.device_put(value, NamedSharding(new_mesh, P()))

    return jax.tree_util.tree_map(
        move, state, is_leaf=lambda x: isinstance(x, _HostStaged)
    )


class LiveStateHandoff:
    """One planned-resize handoff: capture on the old world, apply on the
    new — skipping the checkpoint-restore round trip.

    `capture` is zero-copy (device arrays are kept by reference) and
    records the step so the recipient can arbitrate against the newest
    durable checkpoint. `stage_to_host` exists for teardown paths where
    donor devices are about to vanish: ONLY leaves with at least one owner
    outside the surviving set are pulled to host (the snapshot is scoped
    to shards whose owner set changes; everything else stays on-device).
    `apply` reshards everything onto the new mesh via `reshard_state` and
    consumes the capture (one-shot)."""

    def __init__(self):
        self._state: Any = None
        self._step: Optional[int] = None

    @property
    def captured(self) -> bool:
        return self._state is not None

    @property
    def step(self) -> Optional[int]:
        return self._step

    def capture(self, state: Any) -> "LiveStateHandoff":
        self._state = state
        # host sync — callers sit at a task/step boundary by construction
        self._step = int(jax.device_get(state.step)) if hasattr(
            state, "step") else None
        return self

    def stage_to_host(self, surviving_device_ids) -> int:
        """Pull to host the leaves with any owner OUTSIDE the surviving
        device set; returns how many leaves were staged. In-process
        resizes never need this (device_put reads donors directly);
        teardown paths call it before the old world dies."""
        surviving = set(int(d) for d in surviving_device_ids)
        staged = 0

        def maybe_stage(x):
            nonlocal staged
            if not isinstance(x, jax.Array):
                return x
            owners = {int(d.id) for d in x.sharding.device_set}
            if owners <= surviving:
                return x
            staged += 1
            return _HostStaged(np.asarray(jax.device_get(x)), _leaf_spec(x))

        with tracing.span("handoff.stage_to_host") as sp:
            self._state = jax.tree_util.tree_map(maybe_stage, self._state)
            sp.set(staged_leaves=staged)
        _HANDOFF_STAGED.inc(staged)
        return staged

    def apply(self, new_mesh) -> Any:
        """Reshard the captured state onto `new_mesh`; consumes the
        capture so stale donors cannot be applied twice."""
        if self._state is None:
            raise RuntimeError("LiveStateHandoff.apply with nothing captured")
        state, self._state = self._state, None
        return reshard_state(state, new_mesh)

    def discard(self) -> None:
        self._state = None
        self._step = None


def context_from_env(cfg) -> Optional[CohortContext]:
    """Build the context for this process from config + env (the process
    manager exports EDL_PROCESS_ID per spawned cohort member).

    `EDL_NUM_PROCESSES` overrides `cfg.num_processes`: dynamic world
    resizing re-forms the cohort at a DIFFERENT size than the config's
    original — the manager tells each member the new world size through the
    environment so the argv (which is the job's immutable config) stays
    untouched. `EDL_WORLD_VERSION` carries the generation counter for logs
    and LR-rescale decisions. A resized-to-1 cohort is still a cohort
    (EDL_PROCESS_ID present), so the override may legitimately be 1.
    """
    n = int(os.environ.get("EDL_NUM_PROCESSES", "0") or 0) or cfg.num_processes
    if n <= 1 and "EDL_PROCESS_ID" not in os.environ:
        return None
    if (
        "EDL_PROCESS_ID" not in os.environ
        and os.environ.get("EDL_PROCESS_ID_FROM_HOSTNAME") == "1"
    ):
        # k8s StatefulSet flavor: pods are <name>-<ordinal>; the ordinal IS
        # the cohort process id (stable across pod restarts, which is what
        # makes a StatefulSet the right k8s shape for a jax.distributed
        # world — see client/k8s.py render_worker_statefulset)
        import socket

        host = socket.gethostname()
        ordinal = host.rsplit("-", 1)[-1]
        if ordinal.isdigit():
            os.environ["EDL_PROCESS_ID"] = ordinal
        else:
            raise RuntimeError(
                f"EDL_PROCESS_ID_FROM_HOSTNAME=1 but hostname {host!r} has "
                "no trailing ordinal"
            )
    pid = int(os.environ.get("EDL_PROCESS_ID", "0"))
    addr = (
        os.environ.get("EDL_COORDINATOR_ADDR")
        or cfg.coordinator_addr
        or "localhost:29400"
    )
    version = int(os.environ.get("EDL_WORLD_VERSION", "0") or 0)
    return CohortContext(addr, n, pid, world_version=version)
