"""Multi-process distributed context: one global mesh across worker
processes, with re-formation as the unit of elastic recovery.

Reference parity: the reference's allreduce mode ran one Horovod ring across
worker pods (NCCL/Gloo), re-built by a master-hosted rendezvous when
membership changed (SURVEY §3.4). The TPU-native rebuild uses
`jax.distributed` + ONE `jax.sharding.Mesh` over every process's devices;
gradient averaging is the `psum` XLA inserts over the `data` axis (ICI
in-slice, DCN across hosts). XLA's world is static per initialize(), so
elasticity = re-formation: tear the world down, re-initialize with the new
process set, restore from the latest checkpoint, resume at the exact task
boundary (the task queue makes this data-loss-free).

A worker cohort (elasticdl_tpu/worker/cohort.py) runs SPMD: every process
executes the same jitted steps; per-process data enters as process-local
shards of the global batch via `make_global_batch`.
"""

from __future__ import annotations

import os
from collections.abc import Mapping
from typing import Any, Dict, Optional, Sequence

import jax
import numpy as np

from elasticdl_tpu.common.log_utils import default_logger
from elasticdl_tpu.parallel import mesh as mesh_lib

logger = default_logger(__name__)


class CohortContext:
    """The per-process handle on the distributed world."""

    def __init__(self, coordinator_addr: str, num_processes: int,
                 process_id: int, world_version: int = 0):
        self.coordinator_addr = coordinator_addr
        self.num_processes = num_processes
        self.process_id = process_id
        self.world_version = world_version
        self._initialized = False

    # ------------------------------------------------------------------ #

    def initialize(self) -> None:
        """jax.distributed.initialize — collective, blocks until every
        process of the world version has joined."""
        jax.distributed.initialize(
            coordinator_address=self.coordinator_addr,
            num_processes=self.num_processes,
            process_id=self.process_id,
        )
        self._initialized = True
        logger.info(
            "distributed world v%d up: process %d/%d, %d global devices",
            self.world_version, self.process_id, self.num_processes,
            len(jax.devices()),
        )

    def shutdown(self) -> None:
        if self._initialized:
            jax.distributed.shutdown()
            self._initialized = False

    @property
    def is_leader(self) -> bool:
        return self.process_id == 0

    # ------------------------------------------------------------------ #

    def global_mesh(self, axis_sizes: Optional[Dict[str, int]] = None):
        """Mesh over ALL processes' devices (default: 1-D data axis)."""
        return mesh_lib.build_mesh(axis_sizes, jax.devices())

    def broadcast_ints(self, values: Sequence[int]) -> np.ndarray:
        """Leader -> all: small int64 control vector (the cohort's task/
        checkpoint/LR protocol rides this).

        Shipped as int32 HALVES: with jax_enable_x64 off (the default,
        everywhere in this repo), an int64 array entering
        broadcast_one_to_all is canonicalized to int32 — silently wrapping
        anything past 2^31 (float64 LR bit-patterns; record spans of a
        Criteo-1TB-sized file). Splitting each value into two int32s keeps
        the full 64 bits across the wire."""
        from jax.experimental import multihost_utils

        arr = np.ascontiguousarray(np.asarray(values, np.int64))
        halves = arr.view(np.int32)            # (2n,), little-endian pairs
        out = np.asarray(
            multihost_utils.broadcast_one_to_all(
                halves, is_source=self.is_leader
            ),
            dtype=np.int32,
        )
        return np.ascontiguousarray(out).view(np.int64)

    def barrier(self, name: str) -> None:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)


def make_global_batch(mesh, batch: Any, partition=None) -> Any:
    """Assemble a global sharded batch from each process's IDENTICAL host
    batch: every process holds the same full global batch (readers are
    deterministic), so each local device simply pulls its own slice via
    `make_array_from_callback` — correct for ANY partition spec (data, seq,
    or mixed axes across the process boundary), with no cross-process data
    motion.

    Single-process meshes fall through to the ordinary shard_batch path.
    """
    if jax.process_count() == 1:
        return mesh_lib.shard_batch(mesh, batch, partition)

    from jax.sharding import NamedSharding

    def put(x, sharding):
        x = np.asarray(x)
        return jax.make_array_from_callback(
            x.shape, sharding, lambda idx: x[idx]
        )

    if not isinstance(batch, Mapping):
        # non-dict host batch (bare array / tuple pytree): per-key partition
        # overrides can't apply, so the whole tree takes the default batch
        # spec — mirrors shard_batch's partition=None path
        sh = NamedSharding(mesh, mesh_lib.batch_key_spec(mesh, "", partition))
        return jax.tree_util.tree_map(lambda x: put(x, sh), batch)
    out = {}
    for key, value in batch.items():
        sh = NamedSharding(mesh, mesh_lib.batch_key_spec(mesh, key, partition))
        out[key] = jax.tree_util.tree_map(lambda x, s=sh: put(x, s), value)
    return out


def make_global_batch_stack(mesh, batches, partition=None) -> Any:
    """K identical-on-every-process host batches -> one global pytree with
    a leading step axis (leaves (K, B, ...), sharded P(None, <batch spec>))
    for `Trainer.train_many` — the multi-process twin of
    `mesh.shard_batch_stack`, assembled per-device like make_global_batch."""
    if jax.process_count() == 1:
        return mesh_lib.shard_batch_stack(mesh, batches, partition)

    from jax.sharding import NamedSharding, PartitionSpec as P

    def put(leaves, spec):
        x = np.stack([np.asarray(l) for l in leaves])
        sh = NamedSharding(mesh, P(None, *spec))
        return jax.make_array_from_callback(x.shape, sh, lambda idx: x[idx])

    if not isinstance(batches[0], Mapping):
        # non-dict batches: default data spec on every leaf (matches
        # make_global_batch / mesh.shard_batch_stack fallbacks)
        spec = mesh_lib.batch_key_spec(mesh, "", partition)
        return jax.tree_util.tree_map(
            lambda *ls: put(ls, spec), *batches)
    out = {}
    for key in batches[0]:
        spec = mesh_lib.batch_key_spec(mesh, key, partition)
        out[key] = jax.tree_util.tree_map(
            lambda *ls, s=spec: put(ls, s), *(b[key] for b in batches))
    return out


def context_from_env(cfg) -> Optional[CohortContext]:
    """Build the context for this process from config + env (the process
    manager exports EDL_PROCESS_ID per spawned cohort member).

    `EDL_NUM_PROCESSES` overrides `cfg.num_processes`: dynamic world
    resizing re-forms the cohort at a DIFFERENT size than the config's
    original — the manager tells each member the new world size through the
    environment so the argv (which is the job's immutable config) stays
    untouched. `EDL_WORLD_VERSION` carries the generation counter for logs
    and LR-rescale decisions. A resized-to-1 cohort is still a cohort
    (EDL_PROCESS_ID present), so the override may legitimately be 1.
    """
    n = int(os.environ.get("EDL_NUM_PROCESSES", "0") or 0) or cfg.num_processes
    if n <= 1 and "EDL_PROCESS_ID" not in os.environ:
        return None
    if (
        "EDL_PROCESS_ID" not in os.environ
        and os.environ.get("EDL_PROCESS_ID_FROM_HOSTNAME") == "1"
    ):
        # k8s StatefulSet flavor: pods are <name>-<ordinal>; the ordinal IS
        # the cohort process id (stable across pod restarts, which is what
        # makes a StatefulSet the right k8s shape for a jax.distributed
        # world — see client/k8s.py render_worker_statefulset)
        import socket

        host = socket.gethostname()
        ordinal = host.rsplit("-", 1)[-1]
        if ordinal.isdigit():
            os.environ["EDL_PROCESS_ID"] = ordinal
        else:
            raise RuntimeError(
                f"EDL_PROCESS_ID_FROM_HOSTNAME=1 but hostname {host!r} has "
                "no trailing ordinal"
            )
    pid = int(os.environ.get("EDL_PROCESS_ID", "0"))
    addr = (
        os.environ.get("EDL_COORDINATOR_ADDR")
        or cfg.coordinator_addr
        or "localhost:29400"
    )
    version = int(os.environ.get("EDL_WORLD_VERSION", "0") or 0)
    return CohortContext(addr, n, pid, world_version=version)
