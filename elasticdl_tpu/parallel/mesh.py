"""Device mesh construction and canonical shardings.

TPU-native replacement for the reference's two communication fabrics:
- Horovod/NCCL allreduce rings (reference: elasticdl/python/worker/allreduce_trainer.py)
  become the `data` mesh axis — gradient averaging is XLA `psum` over ICI.
- Parameter-server placement of dense/embedding state
  (reference: elasticdl/pkg/ps/server.go) becomes `NamedSharding`s over the
  same mesh: dense params replicated, embedding rows sharded.

The mesh is the single source of truth for parallelism; everything downstream
(trainer, embedding engine, checkpointing) takes it as input.
"""

from __future__ import annotations

from collections.abc import Mapping
from typing import Dict, Optional, Sequence

import jax
from elasticdl_tpu.common import jax_compat

jax_compat.ensure()  # older-jax API adapters (no-op on current jax)
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from elasticdl_tpu.common.constants import MeshAxis


def build_mesh(
    axis_sizes: Optional[Dict[str, int]] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a Mesh over `devices` (default: all local+remote devices).

    `axis_sizes` maps axis name -> size; default puts every device on the
    `data` axis. A 2-D {"data": d, "model": m} mesh lays `model` innermost so
    embedding all-to-alls ride the fastest ICI links.
    """
    devices = list(devices if devices is not None else jax.devices())
    if not axis_sizes:
        axis_sizes = {MeshAxis.DATA: len(devices)}
    names = tuple(axis_sizes.keys())
    sizes = tuple(axis_sizes.values())
    total = int(np.prod(sizes))
    if total != len(devices):
        raise ValueError(f"mesh {dict(axis_sizes)} needs {total} devices, have {len(devices)}")
    dev_array = np.asarray(devices).reshape(sizes)
    return Mesh(dev_array, names)


def build_hybrid_mesh(
    ici_axis_sizes: Dict[str, int],
    dcn_axis_sizes: Dict[str, int],
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Multi-slice mesh: each named axis is the product of its ICI (within-
    slice) and DCN (across-slice) factors, with the DCN factor slowest-
    varying — collectives on such an axis decompose hierarchically (XLA
    reduces within each slice over ICI first, then once across slices over
    DCN), which is the standard TPU multi-pod recipe: put data-parallel
    across slices ({"data": n_slices} in `dcn_axis_sizes`) and keep
    model/seq sharding inside a slice's ICI.

    Replaces the reference's flat NCCL/Gloo world (reference:
    elasticdl/python/collective_ops/ + Horovod ring over whatever network
    exists) with an explicitly two-tier fabric. On real multi-slice TPU the
    device order comes from `mesh_utils.create_hybrid_device_mesh` (honors
    slice_index); elsewhere (CPU meshes, single slice) the same layout is
    built by grouping `devices` into contiguous per-slice blocks.
    """
    names = tuple(
        dict.fromkeys(tuple(ici_axis_sizes) + tuple(dcn_axis_sizes))
    )
    ici = tuple(int(ici_axis_sizes.get(a, 1)) for a in names)
    dcn = tuple(int(dcn_axis_sizes.get(a, 1)) for a in names)
    devices = list(devices if devices is not None else jax.devices())
    total = int(np.prod(ici)) * int(np.prod(dcn))
    if total != len(devices):
        raise ValueError(
            f"hybrid mesh ici={dict(ici_axis_sizes)} x "
            f"dcn={dict(dcn_axis_sizes)} needs {total} devices, "
            f"have {len(devices)}"
        )
    try:
        from jax.experimental import mesh_utils

        dev_array = mesh_utils.create_hybrid_device_mesh(
            ici, dcn, devices=devices, allow_split_physical_axes=True,
        )
    except Exception:
        # virtual/CPU devices carry no slice topology: contiguous blocks of
        # prod(ici) devices act as slices, then per-axis (dcn_i, ici_i)
        # pairs collapse into one axis with dcn slowest-varying
        arr = np.asarray(devices).reshape(dcn + ici)
        n = len(names)
        perm = [k for i in range(n) for k in (i, n + i)]
        dev_array = arr.transpose(perm).reshape(
            tuple(d * s for d, s in zip(dcn, ici))
        )
    return Mesh(dev_array, names)


def build_job_mesh(cfg, devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """The mesh a job's config asks for: flat (`--mesh_shape`) or hybrid
    multi-slice (`--dcn_mesh_shape` names the across-slice factors, and
    `--mesh_shape` then describes ONE slice's ICI layout). The single entry
    point used by the worker and cohort paths."""
    devices = list(devices if devices is not None else jax.devices())
    dcn = cfg.dcn_axes_sizes()
    if dcn:
        n_slices = int(np.prod(list(dcn.values())))
        if len(devices) % n_slices:
            raise ValueError(
                f"dcn_mesh_shape {cfg.dcn_mesh_shape!r} implies {n_slices} "
                f"slices, which does not divide {len(devices)} devices"
            )
        per_slice = len(devices) // n_slices
        ici = (
            cfg.mesh_axes_sizes(per_slice)
            if cfg.mesh_shape else {MeshAxis.DATA: per_slice}
        )
        return build_hybrid_mesh(ici, dcn, devices)
    return build_mesh(
        cfg.mesh_axes_sizes(len(devices)) if cfg.mesh_shape else None,
        devices,
    )


def data_axis(mesh: Mesh) -> str:
    return MeshAxis.DATA if MeshAxis.DATA in mesh.axis_names else mesh.axis_names[0]


def model_axis(mesh: Mesh) -> Optional[str]:
    return MeshAxis.MODEL if MeshAxis.MODEL in mesh.axis_names else None


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (batch) dim over the data axis."""
    return NamedSharding(mesh, P(data_axis(mesh)))


def table_sharding(mesh: Mesh) -> NamedSharding:
    """Embedding tables: rows sharded over every mesh axis.

    With a 1-D ("data",) mesh this is DLRM-style 'tables sharded across all
    chips, dense replicated'; with ("data", "model") rows shard over both.
    Replaces the reference's `id % ps_num` row placement
    (reference: elasticdl/python/worker/ps_client.py) with a contiguous
    row-range shard per device — contiguous ranges keep XLA gathers dense.
    """
    return NamedSharding(mesh, P(mesh.axis_names, None))


def batch_key_spec(mesh: Mesh, key: str, partition) -> P:
    """THE per-key batch sharding rule, shared by every batch-placement
    path (shard_batch, shard_batch_stack, elastic.make_global_batch and its
    stack twin): a `partition` override for the key (pruned to the mesh's
    axes) or the default P(data_axis)."""
    if partition and partition.get(key) is not None:
        return prune_spec(mesh, partition[key])
    return P(data_axis(mesh))


def shard_batch(mesh: Mesh, batch, partition=None):
    """Device-put a host batch (pytree of np arrays) with batch sharding.

    `partition` optionally overrides the sharding per TOP-LEVEL key with a
    PartitionSpec (models with a sequence-parallel axis shard tokens
    P('data','seq') — see the transformer zoo's batch_partition). Leaves
    already resident with the right sharding pass through untouched (the
    DevicePrefetcher hands the trainer pre-sharded batches)."""
    def put_with(sh):
        def put(x):
            if isinstance(x, jax.Array) and x.sharding == sh:
                return x
            return jax.device_put(x, sh)
        return put

    if not partition or not isinstance(batch, Mapping):
        # per-key overrides only apply to dict batches; a bare-array/tuple
        # batch takes the default data sharding on every leaf
        return jax.tree_util.tree_map(put_with(batch_sharding(mesh)), batch)
    out = {}
    for key, value in batch.items():
        sh = NamedSharding(mesh, batch_key_spec(mesh, key, partition))
        out[key] = jax.tree_util.tree_map(put_with(sh), value)
    return out


def shard_batch_stack(mesh: Mesh, batches, partition=None):
    """Stack K host batches into one pytree with a leading step axis —
    leaves (K, B, ...), device_put as P(None, <batch spec>) — for
    `Trainer.train_many` (one dispatch runs all K steps via lax.scan)."""
    if not isinstance(batches[0], Mapping):
        # non-dict batches: default data spec on every leaf (matches
        # shard_batch's fallback)
        sh = NamedSharding(mesh, P(None, *batch_key_spec(mesh, "", partition)))

        def put_all(*leaves):
            return jax.device_put(
                np.stack([np.asarray(l) for l in leaves]), sh
            )

        return jax.tree_util.tree_map(put_all, *batches)
    out = {}
    for key in batches[0]:
        spec = batch_key_spec(mesh, key, partition)
        sh = NamedSharding(mesh, P(None, *spec))

        def put(*leaves, _sh=sh):
            return jax.device_put(
                np.stack([np.asarray(l) for l in leaves]), _sh
            )

        out[key] = jax.tree_util.tree_map(put, *(b[key] for b in batches))
    return out


def abstract_batch(mesh: Mesh, batch, partition=None):
    """ShapeDtypeStruct mirror of `shard_batch(mesh, batch)`: same leaves,
    same NamedShardings, zero data movement. This is what execution-free
    AOT lowering consumes (rescale fast path: a speculative compile for a
    neighbor world must not device_put onto devices it cannot execute
    on)."""
    def sds_with(sh):
        def sds(x):
            x = x if hasattr(x, "shape") else np.asarray(x)
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sh)
        return sds

    if not partition or not isinstance(batch, Mapping):
        return jax.tree_util.tree_map(sds_with(batch_sharding(mesh)), batch)
    out = {}
    for key, value in batch.items():
        sh = NamedSharding(mesh, batch_key_spec(mesh, key, partition))
        out[key] = jax.tree_util.tree_map(sds_with(sh), value)
    return out


def abstract_batch_stack(mesh: Mesh, batch, k: int, partition=None):
    """ShapeDtypeStruct mirror of `shard_batch_stack(mesh, [batch]*k)`:
    leaves (K, B, ...) with P(None, <batch spec>) shardings, no data."""
    def sds_with(spec):
        sh = NamedSharding(mesh, P(None, *spec))

        def sds(x):
            x = x if hasattr(x, "shape") else np.asarray(x)
            return jax.ShapeDtypeStruct((k,) + tuple(x.shape), x.dtype,
                                        sharding=sh)
        return sds

    if not isinstance(batch, Mapping):
        return jax.tree_util.tree_map(
            sds_with(batch_key_spec(mesh, "", partition)), batch)
    out = {}
    for key, value in batch.items():
        out[key] = jax.tree_util.tree_map(
            sds_with(batch_key_spec(mesh, key, partition)), value)
    return out


def prune_spec(mesh: Mesh, spec: P) -> P:
    """Drop spec axes the mesh doesn't have: the same zoo config (e.g. tokens
    P('data','seq')) runs on a pure-data mesh without a seq axis."""
    entries = []
    for e in spec:
        if e is None:
            entries.append(None)
        else:
            axes = tuple(a for a in (e if isinstance(e, tuple) else (e,))
                         if a in mesh.axis_names)
            entries.append(axes if axes else None)
    return P(*entries)
