"""Device mesh construction and canonical shardings.

TPU-native replacement for the reference's two communication fabrics:
- Horovod/NCCL allreduce rings (reference: elasticdl/python/worker/allreduce_trainer.py)
  become the `data` mesh axis — gradient averaging is XLA `psum` over ICI.
- Parameter-server placement of dense/embedding state
  (reference: elasticdl/pkg/ps/server.go) becomes `NamedSharding`s over the
  same mesh: dense params replicated, embedding rows sharded.

The mesh is the single source of truth for parallelism; everything downstream
(trainer, embedding engine, checkpointing) takes it as input.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from elasticdl_tpu.common.constants import MeshAxis


def build_mesh(
    axis_sizes: Optional[Dict[str, int]] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a Mesh over `devices` (default: all local+remote devices).

    `axis_sizes` maps axis name -> size; default puts every device on the
    `data` axis. A 2-D {"data": d, "model": m} mesh lays `model` innermost so
    embedding all-to-alls ride the fastest ICI links.
    """
    devices = list(devices if devices is not None else jax.devices())
    if not axis_sizes:
        axis_sizes = {MeshAxis.DATA: len(devices)}
    names = tuple(axis_sizes.keys())
    sizes = tuple(axis_sizes.values())
    total = int(np.prod(sizes))
    if total != len(devices):
        raise ValueError(f"mesh {dict(axis_sizes)} needs {total} devices, have {len(devices)}")
    dev_array = np.asarray(devices).reshape(sizes)
    return Mesh(dev_array, names)


def data_axis(mesh: Mesh) -> str:
    return MeshAxis.DATA if MeshAxis.DATA in mesh.axis_names else mesh.axis_names[0]


def model_axis(mesh: Mesh) -> Optional[str]:
    return MeshAxis.MODEL if MeshAxis.MODEL in mesh.axis_names else None


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (batch) dim over the data axis."""
    return NamedSharding(mesh, P(data_axis(mesh)))


def table_sharding(mesh: Mesh) -> NamedSharding:
    """Embedding tables: rows sharded over every mesh axis.

    With a 1-D ("data",) mesh this is DLRM-style 'tables sharded across all
    chips, dense replicated'; with ("data", "model") rows shard over both.
    Replaces the reference's `id % ps_num` row placement
    (reference: elasticdl/python/worker/ps_client.py) with a contiguous
    row-range shard per device — contiguous ranges keep XLA gathers dense.
    """
    return NamedSharding(mesh, P(mesh.axis_names, None))


def shard_batch(mesh: Mesh, batch, partition=None):
    """Device-put a host batch (pytree of np arrays) with batch sharding.

    `partition` optionally overrides the sharding per TOP-LEVEL key with a
    PartitionSpec (models with a sequence-parallel axis shard tokens
    P('data','seq') — see the transformer zoo's batch_partition). Leaves
    already resident with the right sharding pass through untouched (the
    DevicePrefetcher hands the trainer pre-sharded batches)."""
    default = batch_sharding(mesh)

    def put_with(sh):
        def put(x):
            if isinstance(x, jax.Array) and x.sharding == sh:
                return x
            return jax.device_put(x, sh)
        return put

    if not partition:
        return jax.tree_util.tree_map(put_with(default), batch)
    out = {}
    for key, value in batch.items():
        spec = partition.get(key)
        sh = (
            NamedSharding(mesh, prune_spec(mesh, spec))
            if spec is not None else default
        )
        out[key] = jax.tree_util.tree_map(put_with(sh), value)
    return out


def prune_spec(mesh: Mesh, spec: P) -> P:
    """Drop spec axes the mesh doesn't have: the same zoo config (e.g. tokens
    P('data','seq')) runs on a pure-data mesh without a seq axis."""
    entries = []
    for e in spec:
        if e is None:
            entries.append(None)
        else:
            axes = tuple(a for a in (e if isinstance(e, tuple) else (e,))
                         if a in mesh.axis_names)
            entries.append(axes if axes else None)
    return P(*entries)
