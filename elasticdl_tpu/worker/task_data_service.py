"""Turn leased tasks into fixed-shape device batches.

Reference parity: elasticdl/python/worker/task_data_service.py — converts the
task stream into a continuous data pipeline and attributes records to tasks
so completion is reported exactly when a task's records are consumed. Here a
task is processed as a unit (batches of one task never mix with another's),
which keeps exactly-once accounting trivial; the last partial batch is padded
to static shape with mask=0 rows because XLA recompiles on shape changes.

Pipeline design (round 3; SURVEY §7 hard-part 4): records move in batch-sized
spans, not one at a time. Each span is fetched with the reader's `read_span`
(one contiguous read + vectorized split for file-backed readers) and parsed
with a batch parser (data/parsing.py; C++ kernels that release the GIL). A
small thread pool parses up to `lookahead` spans ahead of the consumer —
order-preserving, so task accounting and determinism are unchanged. With the
GIL released inside the native parse, parser threads scale across cores the
way the reference's tf.data C++ op kernels did.
"""

from __future__ import annotations

import os
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, Iterator, Optional

import numpy as np

from elasticdl_tpu.data import parsing
from elasticdl_tpu.data.reader import AbstractDataReader


def _pad_batch(feats, labels, count: int, batch_size: int):
    """Pad a short batch to `batch_size` by repeating row 0, mask marks real
    rows. Keeps every compiled step shape static."""

    def pad(x):
        if isinstance(x, dict):
            return {k: pad(v) for k, v in x.items()}
        reps = np.repeat(x[:1], batch_size - count, axis=0)
        return np.concatenate([x, reps], axis=0)

    mask = np.zeros((batch_size,), np.float32)
    mask[:count] = 1.0
    return pad(feats), pad(labels), mask


class TaskDataService:
    def __init__(
        self,
        reader: AbstractDataReader,
        parse_fn,
        batch_size: int,
        batch_multiple: int = 1,
        num_parallel: int = 0,
    ):
        self._reader = reader
        # Per-record parsers are upgraded to the batch interface; batch
        # parsers (parsing.is_batch_parser) are used as-is.
        self._parse_batch = parsing.as_batch_parser(parse_fn)
        # batch must stay divisible by the mesh's data-axis size
        self._batch_size = max(batch_size, batch_multiple)
        if self._batch_size % batch_multiple:
            self._batch_size += batch_multiple - self._batch_size % batch_multiple
        if num_parallel <= 0:
            num_parallel = min(4, os.cpu_count() or 1)
        if not getattr(reader, "THREAD_SAFE_SPANS", False):
            # stateful readers (RecordIO's shared per-shard handles + LRU)
            # must not serve concurrent span reads — parse serially for them
            num_parallel = 1
        self._num_parallel = num_parallel
        self._pool: Optional[ThreadPoolExecutor] = None

    @property
    def batch_size(self) -> int:
        return self._batch_size

    def close(self) -> None:
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None

    def _make_batch(self, shard_name: str, start: int, end: int) -> Dict[str, Any]:
        records = None
        if getattr(self._parse_batch, "accepts_blob", False):
            # fixed-width fast path: one contiguous read, no record splitting
            records = self._reader.read_block(shard_name, start, end)
        if records is None:
            records = self._reader.read_span(shard_name, start, end)
        feats, labels = self._parse_batch(records)
        count = len(labels)
        if count == self._batch_size:
            mask = np.ones((self._batch_size,), np.float32)
        else:
            feats, labels, mask = _pad_batch(feats, labels, count, self._batch_size)
        return {"features": feats, "labels": labels, "mask": mask}

    def batches(
        self, shard_name: str, start: int, end: int
    ) -> Iterator[Dict[str, Any]]:
        spans = [
            (s, min(s + self._batch_size, end))
            for s in range(start, end, self._batch_size)
        ]
        if self._num_parallel <= 1 or len(spans) <= 1:
            for s, e in spans:
                yield self._make_batch(shard_name, s, e)
            return
        if self._pool is None:
            self._pool = ThreadPoolExecutor(
                max_workers=self._num_parallel,
                thread_name_prefix="edl-parse",
            )
        # Bounded in-flight window, yielded in submission order: lookahead
        # overlaps read+parse of the next spans with the consumer's step, and
        # bounding it caps host memory at ~window batches.
        lookahead = self._num_parallel + 1
        inflight: deque = deque()
        it = iter(spans)
        try:
            for s, e in it:
                inflight.append(self._pool.submit(self._make_batch, shard_name, s, e))
                if len(inflight) >= lookahead:
                    yield inflight.popleft().result()
            while inflight:
                yield inflight.popleft().result()
        finally:
            # Consumer abandoned the generator (task drained/worker exiting):
            # drop queued work so the pool doesn't parse spans nobody reads.
            for fut in inflight:
                fut.cancel()
