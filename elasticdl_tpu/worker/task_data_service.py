"""Turn leased tasks into fixed-shape device batches.

Reference parity: elasticdl/python/worker/task_data_service.py — converts the
task stream into a continuous data pipeline and attributes records to tasks
so completion is reported exactly when a task's records are consumed. Here a
task is processed as a unit (batches of one task never mix with another's),
which keeps exactly-once accounting trivial; the last partial batch is padded
to static shape with mask=0 rows because XLA recompiles on shape changes.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterator, List, Optional

import numpy as np

from elasticdl_tpu.data.reader import AbstractDataReader


def _stack(values: List[Any]):
    if isinstance(values[0], dict):
        return {k: _stack([v[k] for v in values]) for k in values[0]}
    return np.stack(values)


def _pad_batch(feats, labels, count: int, batch_size: int):
    """Pad a short batch to `batch_size` by repeating row 0, mask marks real
    rows. Keeps every compiled step shape static."""

    def pad(x):
        if isinstance(x, dict):
            return {k: pad(v) for k, v in x.items()}
        reps = np.repeat(x[:1], batch_size - count, axis=0)
        return np.concatenate([x, reps], axis=0)

    mask = np.zeros((batch_size,), np.float32)
    mask[:count] = 1.0
    return pad(feats), pad(labels), mask


class TaskDataService:
    def __init__(
        self,
        reader: AbstractDataReader,
        parse_fn: Callable[[bytes], Any],
        batch_size: int,
        batch_multiple: int = 1,
    ):
        self._reader = reader
        self._parse = parse_fn
        # batch must stay divisible by the mesh's data-axis size
        self._batch_size = max(batch_size, batch_multiple)
        if self._batch_size % batch_multiple:
            self._batch_size += batch_multiple - self._batch_size % batch_multiple

    @property
    def batch_size(self) -> int:
        return self._batch_size

    def batches(
        self, shard_name: str, start: int, end: int
    ) -> Iterator[Dict[str, Any]]:
        feats_buf: List[Any] = []
        labels_buf: List[Any] = []
        for record in self._reader.read_records(shard_name, start, end):
            f, l = self._parse(record)
            feats_buf.append(f)
            labels_buf.append(l)
            if len(feats_buf) == self._batch_size:
                yield {
                    "features": _stack(feats_buf),
                    "labels": _stack(labels_buf),
                    "mask": np.ones((self._batch_size,), np.float32),
                }
                feats_buf, labels_buf = [], []
        if feats_buf:
            f, l, m = _pad_batch(
                _stack(feats_buf), _stack(labels_buf), len(feats_buf), self._batch_size
            )
            yield {"features": f, "labels": l, "mask": m}
