"""The worker: lease tasks from the master, run the jitted step, report back.

Reference parity: elasticdl/python/worker/worker.py — `Worker.run()` loops
`get_task` → build dataset → per-minibatch train step → `report_task_result`,
plus evaluation and prediction task handling. The hot path differs exactly as
SURVEY §3.3 prescribes: no per-step PS pulls/pushes — forward, backward, and
optimizer update are one donated-state XLA program on the local mesh, and the
only RPCs left are one lease + one report per task plus heartbeats.
"""

from __future__ import annotations

import os
import socket
import threading
import time
from collections import deque
from typing import Any, Dict, Optional

import numpy as np

from elasticdl_tpu.common import faults, membership_signal
from elasticdl_tpu.common.config import JobConfig
from elasticdl_tpu.common.constants import WorkerEnv
from elasticdl_tpu.common.log_utils import default_logger
from elasticdl_tpu.data.reader import create_data_reader
from elasticdl_tpu.observability import flight as flight_lib
from elasticdl_tpu.observability import goodput as goodput_lib
from elasticdl_tpu.observability import reqtrace as reqtrace_lib
from elasticdl_tpu.observability import profile as profile_lib
from elasticdl_tpu.observability import timeseries as timeseries_lib
from elasticdl_tpu.observability import tracing
from elasticdl_tpu.observability.health import (
    STATS_METADATA_KEY,
    WorkerStepStats,
    encode_stats,
)
from elasticdl_tpu.observability.registry import default_registry
from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb
from elasticdl_tpu.proto.service import (
    RetryingMasterStub,
    is_stale_generation,
    jittered,
    make_channel,
    register_with_retry,
    reregister,
)
from elasticdl_tpu.training.model_spec import ModelSpec
from elasticdl_tpu.worker.task_data_service import TaskDataService

logger = default_logger(__name__)

_reg = default_registry()
_TRAIN_STEPS = _reg.counter(
    "edl_train_steps_total", "train steps run by this worker")
_TRAIN_RECORDS = _reg.counter(
    "edl_train_records_total", "non-padding records applied")
_TRAIN_THROUGHPUT = _reg.gauge(
    "edl_train_samples_per_sec",
    "per-task mean throughput (records / measured step wall time)")
_TRAIN_STEP_S = _reg.histogram(
    "edl_train_step_seconds", "per-step wall time (dispatch + compute)")
_RESCALES = _reg.counter(
    "edl_rescale_applied_total", "in-place rescales applied")
_RESCALE_S = _reg.histogram(
    "edl_rescale_seconds", "in-place rescale recovery wall time")
_RECONNECTS = _reg.counter(
    "edl_worker_reconnects_total",
    "reconnect handshakes after a master restart (re-register + re-lease)")


class Worker:
    def __init__(self, cfg: JobConfig, mesh=None):
        self.cfg = cfg
        self._mesh = mesh
        self._trainer = None
        self._state = None
        self._spec: Optional[ModelSpec] = None
        self._services: Dict[int, TaskDataService] = {}
        self._stub: Optional[RetryingMasterStub] = None
        self.worker_id = -1
        self._membership_version = -1
        self._shutdown = threading.Event()
        self._heartbeat_thread: Optional[threading.Thread] = None
        self._parse_fns: Dict[str, Any] = {}
        self._ckpt_manager = None
        self._last_ckpt_step = 0
        self._preempted = False
        self._job_done = False
        self._mid_training_task = False
        self._base_lr = None          # injected LR at init (elastic scaling)
        self._pending_lr = None       # set by heartbeat thread, applied by run loop
        self._pushed_lr = 0.0         # last master-pushed LR override seen
        self._last_known_workers = 0  # latest alive count (register/heartbeat)
        self._global_step = 0         # train steps run by this worker
        # Plain-int mirror of state.model_version, maintained by the MAIN
        # thread at state creation/restore and after each step/group. The
        # heartbeat thread must read THIS, never state.model_version:
        # int(state.step) blocks on the in-flight donated computation, so a
        # multi-second dispatch (train_many groups, big compiles) would
        # silently stall heartbeats until the master declares us dead.
        self._model_version = 0
        self._profile_state = "idle"  # idle -> active -> done (jax.profiler)
        self._ckpt_requested = False  # heartbeat should_checkpoint bit
        self._last_master_ok = time.monotonic()  # last successful master RPC
        self._master_lost = False     # unreachable past the config timeout
        # In-place rescale (rescale fast path): a pending (axis_sizes,
        # devices) target applied at the next batch/task boundary — live
        # state handoff + executable-cache reuse, no teardown/restore.
        self._pending_rescale = None
        self.last_recovery_s: Optional[float] = None
        # heartbeat-piggybacked telemetry (observability/health.py): the
        # train loop observes step timings, the heartbeat thread snapshots
        # them into the stats payload the master's straggler scorer reads
        self._step_stats = WorkerStepStats()
        self._rescaling = False       # True while _rescale_in_place runs
        # Batched leases (--task_lease_batch): locally leased tasks still
        # to run — drained before the next GetTask poll. Cleared on every
        # reconnect handshake: a restarted master's replay requeued these
        # leases whole, so running a local copy would be wasted work (its
        # report comes back accepted=False either way).
        self._lease_queue: "deque[pb.Task]" = deque()
        # Elastic sharded embedding tier (cfg.embedding_shards > 0): this
        # worker's owning store + pull/push client. Membership bumps set
        # the refresh flag (heartbeat thread); the run loop reacts at the
        # next task boundary — shard installs must not stall heartbeats.
        self._tier = None
        self._tier_refresh_pending = False

    # ------------------------------------------------------------------ #
    # setup

    def _connect(self) -> None:
        addr = self.cfg.master_addr
        self._channel = make_channel(addr)
        # Hardened stub: per-call deadlines, idempotent-only retries with
        # backoff, circuit breaker. Every successful RPC (on any thread)
        # refreshes the master-unreachable clock through on_success. The
        # channel_factory makes master-restart recovery bounded: repeated
        # transport failures rebuild the channel instead of trusting a
        # subchannel that wedged when the old master's listener vanished.
        self._stub = RetryingMasterStub(
            self._channel, on_success=self._note_master_ok,
            channel_factory=lambda: make_channel(addr),
        )
        # registered once, reused by every reconnect handshake: a renamed
        # re-register would silently overwrite the membership entry's name
        self._name = f"{socket.gethostname()}:{os.getpid()}"
        # gRPC embedding data plane (ISSUE 15): the endpoint comes up
        # BEFORE registration so its address can ride the RegisterWorker
        # request into the owner address book; the store binds later,
        # when the tier runtime builds it (_init_embedding_tier)
        self._start_data_plane()
        preferred = int(os.environ.get(WorkerEnv.WORKER_ID, -1))
        resp = self._boot_register(self._name, preferred)
        self.worker_id = resp.worker_id
        self._membership_version = resp.membership_version
        self._last_known_workers = resp.num_workers
        # role known now: trace spans + JSON logs carry it; a reform trace
        # id announced by the master (membership signal) makes this boot
        # part of the resize's cross-role timeline
        tracing.configure_from_config(
            self.cfg, role=f"worker-{self.worker_id}"
        )
        # flight recorder: the black box dumps on crash/SIGUSR2/endpoint
        # (observability/flight.py trigger matrix); armed as soon as the
        # role is known so even boot failures leave a bundle
        flight_lib.configure_from_config(
            self.cfg, role=f"worker-{self.worker_id}"
        )
        flight_lib.install_crash_hooks()
        # metrics time series (observability/timeseries.py): the process
        # ring behind GET /timeseries + rolling metrics_history.jsonl;
        # sampled from the heartbeat loop (the interval gate makes the
        # per-beat cost a clock read)
        timeseries_lib.configure_from_config(
            self.cfg, role=f"worker-{self.worker_id}"
        )
        logger.info(
            "registered as worker %d (membership v%d, %d workers)",
            self.worker_id, resp.membership_version, resp.num_workers,
        )

    def _start_data_plane(self) -> None:
        """Bind the per-worker EmbeddingData endpoint (next to the
        observability endpoint — both are sidecar servers on daemon
        threads). A bind failure is fatal: `--embedding_transport grpc`
        means peers' shards live in other processes, so silently
        falling back to LocalTransport would leave every peer-owned
        pull/push raising OwnerUnavailableError forever (and peers
        unable to reach our shards) — fail at boot, loudly, instead."""
        self._data_server = None
        if (self.cfg.embedding_shards <= 0
                or self.cfg.embedding_transport != "grpc"):
            return
        try:
            from elasticdl_tpu.embedding.data_plane import (
                EmbeddingDataServer,
            )

            self._data_server = EmbeddingDataServer(
                shm=self.cfg.embedding_shm)
            self._data_server.start()
        except Exception as e:
            self._data_server = None
            raise RuntimeError(
                "embedding data-plane endpoint failed to start but "
                "--embedding_transport grpc requires it (peer-owned "
                f"shards are unreachable over LocalTransport): {e}"
            ) from e

    @property
    def _data_addr(self) -> str:
        srv = getattr(self, "_data_server", None)
        return srv.address or "" if srv is not None else ""

    def _boot_register(self, name: str, preferred: int):
        """Boot-time registration that rides out a master that is down or
        restarting (see proto/service.py's register_with_retry — shared
        with the cohort leader so the handshake cannot diverge)."""
        return register_with_retry(
            self._stub,
            name=name,
            preferred_id=preferred,
            window_s=self.cfg.master_unreachable_timeout_s,
            shutdown=self._shutdown,
            data_addr=self._data_addr,
        )

    def _note_master_ok(self) -> None:
        """RetryingMasterStub success hook (runs on whichever thread made
        the call): the master answered, so the unreachable clock resets."""
        self._last_master_ok = time.monotonic()

    def _master_unreachable(self) -> bool:
        """Called from RPC-failure paths: True (once; also flips
        _master_lost and _shutdown) when no master RPC has succeeded for
        master_unreachable_timeout_s — the master is permanently gone, and
        retrying forever would leave an orphan process spinning on a dead
        address (observed: cohort members surviving hours after their
        master's process tree was killed). Exit EX_TEMPFAIL instead: a live
        manager relaunches us; an orphan frees its chip and memory."""
        limit = self.cfg.master_unreachable_timeout_s
        if limit <= 0 or time.monotonic() - self._last_master_ok < limit:
            return False
        if not self._master_lost:
            self._master_lost = True
            logger.error(
                "no successful master RPC for %.0fs (limit %.0fs): master "
                "presumed gone, exiting EX_TEMPFAIL",
                time.monotonic() - self._last_master_ok, limit,
            )
            self._shutdown.set()
        return True

    def _reregister(self) -> None:
        """The reconnect handshake after a master restart (shared with the
        cohort leader — see proto/service.py's reregister): idempotent
        re-register under our EXISTING worker id, then apply the response."""
        resp = reregister(
            self._stub, name=self._name, worker_id=self.worker_id,
            data_addr=self._data_addr,
        )
        # drop locally queued leases: the restarted master conservatively
        # requeued every lease of the dead generation, so these tasks will
        # re-run (exactly once) through fresh leases
        self._lease_queue.clear()
        self.worker_id = resp.worker_id
        self._membership_version = resp.membership_version
        self._last_known_workers = resp.num_workers or self._last_known_workers
        _RECONNECTS.inc()
        tracing.event(
            "worker.reconnect", worker_id=self.worker_id,
            membership_version=resp.membership_version,
        )
        logger.warning(
            "re-registered with restarted master as worker %d "
            "(membership v%d); resuming leases under the new generation",
            self.worker_id, resp.membership_version,
        )

    def _maybe_reconnect(self, e: BaseException) -> bool:
        """RPC-failure triage for the master-restart fence: True when `e`
        was a stale-generation rejection AND the reconnect handshake ran —
        the caller should retry its loop instead of backing off or dying.
        Any other error (including a failed re-register: the master may
        have crashed AGAIN mid-handshake) returns False and leaves the
        normal unreachable accounting to the caller."""
        if self.worker_id < 0 or not is_stale_generation(e):
            return False
        try:
            self._reregister()
            return True
        except Exception as handshake_err:
            logger.warning(
                "re-register after master restart failed: %s", handshake_err
            )
            self._master_unreachable()
            return False

    def _build_trainer(self) -> None:
        from elasticdl_tpu.common.runtime import configure_jax_runtime
        from elasticdl_tpu.parallel.mesh import build_job_mesh
        import jax

        configure_jax_runtime(self.cfg)
        self._spec = ModelSpec.from_config(self.cfg)
        if self._mesh is None:
            self._mesh = build_job_mesh(self.cfg, jax.devices())
        self._trainer = self._make_trainer(self._mesh)

    def _make_trainer(self, mesh):
        """One Trainer construction path for boot AND in-place rescale: the
        config-derived cache token is what lets the post-rescale trainer
        find the speculatively-compiled executables (compile_cache.py)."""
        from elasticdl_tpu.training import compile_cache as cc
        from elasticdl_tpu.training.trainer import Trainer

        return Trainer(
            self._spec, mesh, remat=self.cfg.remat,
            remat_policy=self.cfg.remat_policy,
            grad_accum=self.cfg.grad_accum_steps, seed=self.cfg.shuffle_seed,
            cache_token=cc.job_cache_token(self.cfg),
        )

    def _data_service(self, task_type: int) -> TaskDataService:
        if task_type not in self._services:
            paths = {
                pb.TRAINING: self.cfg.training_data,
                pb.EVALUATION: self.cfg.validation_data or self.cfg.training_data,
                pb.PREDICTION: self.cfg.prediction_data,
            }
            reader = create_data_reader(
                paths[task_type], self.cfg.data_reader, **self.cfg.data_reader_params
            )
            mode = {
                pb.TRAINING: "training",
                pb.EVALUATION: "evaluation",
                pb.PREDICTION: "prediction",
            }[task_type]
            if self._spec.dataset_fn is None:
                raise ValueError("model module must define dataset_fn for data tasks")
            parse = self._spec.dataset_fn(mode, reader.metadata)
            from elasticdl_tpu.parallel.mesh import data_axis

            multiple = dict(
                zip(self._mesh.axis_names, self._mesh.devices.shape)
            )[data_axis(self._mesh)]
            self._services[task_type] = TaskDataService(
                reader, parse, self.cfg.minibatch_size, batch_multiple=multiple
            )
        return self._services[task_type]

    def _prefetched(self, batches):
        """Overlap host->device transfer with compute (data/prefetch.py).
        Batches arrive pre-sharded, so the train step's shard_batch is a
        no-op for them. Depth/cast come from the config, overridable via
        EDL_PREFETCH_DEPTH / EDL_PREFETCH_CAST (env wins — operators tune
        the lookahead without touching the job's immutable argv)."""
        from elasticdl_tpu.data.prefetch import prefetch_to_device

        depth = (None if "EDL_PREFETCH_DEPTH" in os.environ
                 else self.cfg.prefetch_batches)
        cast = (None if "EDL_PREFETCH_CAST" in os.environ
                else self.cfg.wire_dtype)
        return prefetch_to_device(
            self._mesh, batches, depth, cast=cast,
            partition=self._spec.batch_partition if self._spec else None,
        )

    def _checkpoint_manager(self):
        if self._ckpt_manager is None and self.cfg.checkpoint_dir:
            from elasticdl_tpu.training.checkpoint import CheckpointManager

            self._ckpt_manager = CheckpointManager(
                self.cfg.checkpoint_dir, keep=self.cfg.keep_checkpoint_max
            )
        return self._ckpt_manager

    def _ensure_state(self, example_batch: Dict[str, Any]) -> None:
        if self._state is not None:
            return
        self._state = self._trainer.init_state(example_batch)
        if self.cfg.scale_lr_with_workers and self._base_lr is None:
            from elasticdl_tpu.training.lr_modulation import get_learning_rate

            # Read the CONFIGURED base LR from the freshly-initialized state,
            # before checkpoint restore — a restored opt_state may already
            # carry an elastically scaled LR, and re-basing on it would
            # compound the scaling across relaunches.
            self._base_lr = get_learning_rate(self._state.opt_state)
            if self._base_lr is None:
                logger.warning(
                    "scale_lr_with_workers needs an optimizer built via "
                    "lr_modulation.modulated(...); LR scaling disabled"
                )
        # Elastic recovery: a relaunched worker resumes from the latest
        # checkpoint instead of fresh params (reference analog: rank-0
        # Horovod broadcast after re-rendezvous restoring replicated state).
        mngr = self._checkpoint_manager()
        if mngr is not None and mngr.latest_step() is not None:
            restored = mngr.restore(self._state)
            if restored is not None:
                self._state = restored
                self._last_ckpt_step = self._state.model_version
                logger.info(
                    "resumed from checkpoint at step %d", self._last_ckpt_step
                )
                if (self.cfg.scale_lr_with_workers and self._base_lr
                        and not self._pushed_lr):
                    from elasticdl_tpu.training.lr_modulation import linear_scale

                    # the restored opt_state may carry an LR scaled for a
                    # membership that no longer exists; re-derive it from the
                    # CURRENT worker count seen at registration (unless a
                    # master LR push is active — it wins)
                    self._pending_lr = linear_scale(
                        self._base_lr,
                        self._last_known_workers or self.cfg.num_workers,
                        self.cfg.num_workers,
                    )
        self._model_version = self._state.model_version

    def _maybe_checkpoint(self, force: bool = False) -> None:
        """Step-interval checkpointing (reference: --checkpoint_steps), plus
        forced saves on preemption — both taken only at task boundaries.

        Only worker 0 writes interval/preemption checkpoints: concurrent
        orbax managers over one directory race on saves and max_to_keep GC
        (the reference had the same single-writer shape — its master owned
        checkpointing). Every worker still *restores*. Master-coordinated
        SAVE_MODEL tasks (exclusive lease) may be served by any worker.
        force=True also drains any in-flight async save, so a preemption
        exit never abandons a half-written checkpoint."""
        if force and self._tier is not None:
            # the tier half of a forced save: every worker persists ITS
            # resident shards (one owner per shard — no write races),
            # seq watermarks included, so a planned kill loses no acked
            # push (the kill-worker resharding acceptance)
            try:
                self._tier.drain()
            except Exception:
                logger.exception("embedding tier drain failed")
        mngr = self._checkpoint_manager()
        if mngr is None or self._state is None or self.worker_id != 0:
            return
        if self._mid_training_task:
            # Never persist mid-task state: the task's lease is only released
            # on report, so a mid-task save + relaunch would re-apply the
            # task's records on top of updates that already include them
            # (double-counting). Saves happen only at task boundaries, where
            # state and the task queue agree exactly-once.
            if force:
                mngr.wait()
            return
        step = self._state.model_version
        due = (
            self.cfg.checkpoint_steps > 0
            and step - self._last_ckpt_step >= self.cfg.checkpoint_steps
        )
        if (force and step > self._last_ckpt_step) or due:
            mngr.save(self._state)
            self._last_ckpt_step = step
        if force:
            mngr.wait()

    # ------------------------------------------------------------------ #
    # heartbeats

    def _stats_payload(self) -> Dict[str, Any]:
        """The heartbeat telemetry payload: recent step-time quantiles +
        records/s from the rolling window, plus the control-plane state
        the master's health layer wants to see (breaker, rescale phase,
        prefetch lookahead, world generation)."""
        stats = self._step_stats.snapshot()
        if self._rescaling or self._pending_rescale is not None:
            phase = "rescale"
        elif self._mid_training_task:
            phase = "train"
        else:
            phase = "idle"
        try:
            depth = int(
                os.environ.get("EDL_PREFETCH_DEPTH", "")
                or self.cfg.prefetch_batches
            )
        except ValueError:
            depth = self.cfg.prefetch_batches
        stats.update(
            phase=phase,
            breaker_open=int(bool(self._stub and self._stub.breaker.is_open)),
            prefetch_depth=depth,
            world_version=tracing.get_tracer().world_version,
        )
        # step-profiler phase breakdown + memory watermarks (bounded key
        # set): the master's ClusterHealth sees WHY a straggler is slow
        stats.update(profile_lib.get_profiler().snapshot())
        # goodput ledger ride-along (ISSUE 12): cumulative per-category
        # wall-clock attribution (gp_* keys) — the master's FleetGoodput
        # rollup totals these into the fleet goodput fraction
        stats.update(goodput_lib.get_ledger().payload())
        # request-diary ride-along (ISSUE 19): compact tail-attribution
        # rollup (rt_* keys) + degraded/shm-fallback shares — the
        # master's FleetAttribution and fleet_series read these
        stats.update(reqtrace_lib.get_recorder().payload())
        # embedding-tier skew ride-along (ISSUE 11): hot-id share, shard
        # imbalance, recent pull/push p99 — the fleet rollup's sensor for
        # the hot-row-cache decision. Best-effort like the rest of the
        # payload: a tier hiccup must never cost the heartbeat.
        if self._tier is not None:
            try:
                stats.update(self._tier.client.tier_stats())
            except Exception:
                # edl-lint: disable=EDL303
                pass
        return stats

    def _heartbeat_loop(self) -> None:
        while not self._shutdown.is_set():
            # time-series sample when due (interval-gated: normally one
            # clock read per beat); rides the heartbeat thread so the
            # train loop never pays for a registry snapshot
            timeseries_lib.get_store().maybe_sample()
            try:
                # chaos hook: worker.heartbeat:crash kills the process here
                # (a hard worker death between task boundaries); drop/delay
                # fall through the same except path as a network failure
                faults.fire("worker.heartbeat")
                # telemetry rides as OPTIONAL metadata: a master that does
                # not understand it ignores it, and a payload-building
                # failure degrades this beat to liveness-only — stats must
                # never cost a heartbeat
                try:
                    md = ((STATS_METADATA_KEY,
                           encode_stats(self._stats_payload())),)
                except Exception:
                    md = None
                resp = self._stub.Heartbeat(
                    pb.HeartbeatRequest(
                        worker_id=self.worker_id,
                        model_version=self._model_version,
                    ),
                    timeout=10,
                    metadata=md,
                )
                if resp.shutdown:
                    logger.info("master requested shutdown")
                    # job_done distinguishes normal completion (export the
                    # final model) from aborts/evictions (don't)
                    if resp.job_done:
                        self._job_done = True
                    self._shutdown.set()
                    break
                if getattr(resp, "evict", False):
                    # graceful-eviction drain handshake (the closed-loop
                    # autoscaler shrinking past this worker): identical to
                    # a k8s SIGTERM preemption — stop at the next batch
                    # boundary, drain-checkpoint, report the applied
                    # prefix (the remainder requeues FRONT, retry-free),
                    # exit EX_TEMPFAIL. The run loop does all of that off
                    # the _preempted flag; this thread only raises it.
                    logger.warning(
                        "master evicted this worker (autoscale policy); "
                        "draining"
                    )
                    tracing.event(
                        "worker.evicted", worker_id=self.worker_id,
                    )
                    self.preempt()
                    break
                self._last_known_workers = resp.num_workers or self._last_known_workers
                if resp.should_checkpoint:
                    # honored by the run loop at the next task boundary (the
                    # heartbeat thread must not save mid-train-step)
                    self._ckpt_requested = True
                if resp.membership_version != self._membership_version:
                    self._on_membership_change(
                        resp.membership_version, resp.num_workers
                    )
                if (
                    resp.learning_rate > 0
                    and resp.learning_rate != self._pushed_lr
                ):
                    # master-pushed LR override (ReduceLROnPlateau): applied
                    # at the next task boundary, AFTER any elastic rescale
                    # set above — the push is job-global and wins
                    self._pushed_lr = resp.learning_rate
                    self._pending_lr = resp.learning_rate
            except Exception as e:
                logger.warning("heartbeat failed: %s", e)
                # a stale-generation fence means the master is BACK (it
                # restarted); re-register instead of counting it toward
                # the unreachable exit
                if not self._maybe_reconnect(e):
                    self._master_unreachable()
            # jittered beat: a synchronized swarm (mass relaunch, master
            # restart) must de-phase instead of arriving as one herd
            self._shutdown.wait(jittered(self.cfg.worker_heartbeat_s))

    def _on_membership_change(self, new_version: int, num_workers: int = 0) -> None:
        """Elastic hook: the worker set changed. This worker's only local
        reaction is rescaling the LR (when scale_lr_with_workers) — its
        single-host mesh keeps running. Multi-process mesh re-formation is
        NOT done here: cohort worlds are torn down and re-formed by the
        instance manager (master/process_manager.py), with worker/cohort.py
        exiting and restoring from checkpoint."""
        logger.info(
            "membership v%d -> v%d", self._membership_version, new_version
        )
        self._membership_version = new_version
        if self._tier is not None:
            # shards may have been re-planned onto (or off) this worker;
            # the run loop executes the refresh at a task boundary
            self._tier_refresh_pending = True
        if (
            self.cfg.scale_lr_with_workers and self._base_lr and num_workers
            and not self._pushed_lr
        ):
            from elasticdl_tpu.training.lr_modulation import linear_scale

            # applied by the run loop at the next task boundary (the
            # heartbeat thread must not swap state mid-train-step). An
            # active master push (ReduceLROnPlateau) wins over the elastic
            # rescale — without this guard a membership bump would silently
            # revert the plateau reduction and the push could never re-fire
            # (resp.learning_rate == self._pushed_lr stays true)
            self._pending_lr = linear_scale(
                self._base_lr, num_workers, self.cfg.num_workers
            )

    # ------------------------------------------------------------------ #
    # in-place rescale (single-process worlds)

    def request_rescale(self, axis_sizes=None, devices=None) -> None:
        """Ask for an in-place mesh rescale, applied at the next batch/task
        boundary by the run/task loops. Single-process worlds only (the
        plain worker owns all its devices): the multi-process cohort
        re-forms through the instance manager instead — its fast path is
        the persistent compile cache + speculative neighbor compilation.
        Thread-safe in the signal-handler sense: just stores the target."""
        self._pending_rescale = (axis_sizes, devices)

    def _rescale_in_place(self, reset_services: bool = True) -> None:
        """Apply a pending rescale without the teardown/checkpoint-restore
        round trip: build the new mesh, hand the live state over
        (parallel/elastic.reshard_state moves only shards whose owner set
        changes), and swap in a Trainer that — sharing the executable
        cache and the config-derived token — reuses any speculatively
        compiled programs instead of re-tracing.

        `reset_services=False` for MID-TASK rescales: the in-flight task's
        source generator belongs to the live data service, and its batch
        shape must stay static anyway; task-boundary rescales rebuild the
        services so batch_multiple re-derives from the new data axis."""
        from elasticdl_tpu.parallel import elastic
        from elasticdl_tpu.parallel.mesh import build_mesh

        target, self._pending_rescale = self._pending_rescale, None
        if target is None:
            return
        axis_sizes, devices = target
        # heartbeat telemetry reports phase="rescale" for the duration
        # (the pending target was just consumed, so the flag is what keeps
        # the master's health view honest mid-recovery)
        self._rescaling = True
        t0 = time.perf_counter()
        # the rescale opens a NEW world generation: bump the tracer's world
        # version first so every span of this recovery carries it — rolled
        # back below if the build fails (the worker keeps running the OLD
        # world then, and telemetry must agree)
        prev_world_version = tracing.get_tracer().world_version
        tracing.set_world_version(prev_world_version + 1)
        # join the master's announced resize timeline when one exists (the
        # membership signal file carries its trace id); otherwise this
        # rescale starts its own trace
        announced_tid = membership_signal.trace_id()
        try:
            # goodput: every second of the rescale lands in the `rescale`
            # category, sub-bucketed settle/compile/handoff to mirror the
            # resize trace's phase vocabulary (the profiler's handoff
            # phase is deliberately NOT teed into the ledger — these
            # explicit adds are the one billing site)
            ledger = goodput_lib.get_ledger()
            with tracing.span(
                "rescale", trace_id=announced_tid,
                mid_task=not reset_services,
            ) as root:
                # build everything fallible FIRST, swap worker state LAST: a
                # failed construction must leave the old mesh/trainer/state
                # fully intact
                with tracing.span("rescale.mesh"), \
                        ledger.phase("rescale", sub="settle"):
                    new_mesh = build_mesh(axis_sizes, devices)
                with tracing.span("rescale.compile"), \
                        ledger.phase("rescale", sub="compile"):
                    # construction resolves the executable cache; an actual
                    # re-trace (cache miss) is deferred to the first step
                    new_trainer = self._make_trainer(new_mesh)
                new_state = self._state
                if new_state is not None:
                    with tracing.span("rescale.handoff"), \
                            ledger.phase("rescale", sub="handoff"):
                        handoff = elastic.LiveStateHandoff().capture(
                            new_state
                        )
                        new_state = handoff.apply(new_mesh)
                self._state = new_state
                self._mesh = new_mesh
                self._trainer = new_trainer
                if reset_services:
                    for svc in self._services.values():
                        svc.close()
                    self._services.clear()
                self.last_recovery_s = time.perf_counter() - t0
                root.set(
                    world_size=int(new_mesh.devices.size),
                    recovery_s=round(self.last_recovery_s, 6),
                )
        except BaseException:
            tracing.set_world_version(prev_world_version)
            raise
        finally:
            self._rescaling = False
        _RESCALES.inc()
        _RESCALE_S.observe(self.last_recovery_s)
        logger.info(
            "in-place rescale to %s in %.3fs (compile cache: %s)",
            dict(zip(new_mesh.axis_names, new_mesh.devices.shape)),
            self.last_recovery_s, self._trainer.compile_stats(),
        )

    # ------------------------------------------------------------------ #
    # task execution

    def _maybe_profile(self) -> None:
        """Drive the jax.profiler trace window (SURVEY §5 tracing): worker 0
        records steps [profile_start_step, profile_start_step+profile_steps)
        into profile_dir, skipping compile/warmup. One window per run."""
        if not self.cfg.profile_dir or self.worker_id != 0:
            return
        import jax

        if (
            self._profile_state == "idle"
            and self._global_step >= self.cfg.profile_start_step
        ):
            try:
                jax.profiler.start_trace(self.cfg.profile_dir)
                self._profile_state = "active"
                logger.info(
                    "profiler trace started at step %d -> %s",
                    self._global_step, self.cfg.profile_dir,
                )
            except Exception:
                logger.exception("profiler start failed; disabled")
                self._profile_state = "done"
        elif (
            self._profile_state == "active"
            and self._global_step
            >= self.cfg.profile_start_step + self.cfg.profile_steps
        ):
            self._stop_profiler()

    def _stop_profiler(self) -> None:
        if self._profile_state != "active":
            return
        import jax

        try:
            jax.profiler.stop_trace()
            logger.info("profiler trace stopped at step %d", self._global_step)
        except Exception:
            logger.exception("profiler stop failed")
        self._profile_state = "done"

    def _run_training_task(self, task: pb.Task) -> Dict[str, float]:
        if self.cfg.steps_per_dispatch > 1:
            return self._run_training_task_grouped(
                task, self.cfg.steps_per_dispatch)
        svc = self._data_service(pb.TRAINING)
        loss_sum, loss_count = 0.0, 0
        records_done = 0
        step_time_sum = 0.0
        interrupted = False
        self._mid_training_task = True
        # always-on step profiler (observability/profile.py): the
        # prefetcher attributes data_wait/h2d internally; this loop
        # attributes compute (the timed step region) and handoff (the
        # mid-task rescale) and closes each step's phase record
        prof = profile_lib.get_profiler()
        prefetcher = self._prefetched(
            svc.batches(task.shard_name, task.start, task.end))
        while True:
            if self._pending_rescale is not None and not self._shutdown.is_set():
                # mid-task in-place rescale: the lookahead window holds
                # device batches with the OLD mesh's shardings — drain it
                # (pending HOST batches come back), rescale, and requeue
                # the drained batches through a prefetcher on the new mesh
                # so the task's record span stays exactly-once. A failed
                # rescale (bad advisory target) must cost a log line, not
                # the task: the drained batches are requeued either way,
                # on whatever mesh the worker ends up holding.
                import itertools

                with prof.phase("handoff"):
                    leftover = prefetcher.drain()
                    source = prefetcher.source
                    try:
                        self._rescale_in_place(reset_services=False)
                    except Exception:
                        logger.exception(
                            "mid-task in-place rescale failed; mesh kept")
                    prefetcher = self._prefetched(
                        itertools.chain(iter(leftover), source))
            try:
                batch = next(prefetcher)
            except StopIteration:
                break
            if self._shutdown.is_set():
                # preemption mid-task: stop before the next batch; the drain
                # report below hands the unprocessed remainder back
                interrupted = True
                break
            self._ensure_state(batch)
            self._maybe_profile()
            t0 = time.perf_counter()
            # straggler-injection site (per-worker so a chaos schedule can
            # slow EXACTLY one worker: worker.train_step.<id>, or all via
            # the worker.train_step.* wildcard); inside the timed region,
            # so an injected delay reads as a slow step — which is the
            # point: the health layer must detect it
            faults.fire(f"worker.train_step.{self.worker_id}")
            self._state, logs = self._trainer.train_step(self._state, batch)
            # float() forces the step's result, so this wall time covers the
            # whole step (dispatch + device compute), not just dispatch —
            # the sync IS the measurement: edl-lint: disable=EDL201
            loss_sum += float(logs["loss"])
            step_s = time.perf_counter() - t0
            step_time_sum += step_s
            _TRAIN_STEP_S.observe(step_s)
            # the already-measured region IS the compute phase — no second
            # timer on the hot path
            prof.add("compute", step_s)
            prof.step_done()
            loss_count += 1
            self._global_step += 1
            self._model_version += 1
            # mask sums the real (non-padding) records this batch applied;
            # exactly-once accounting needs it per batch (the drain report
            # retires records mid-task): edl-lint: disable=EDL201
            batch_records = int(batch["mask"].sum())
            records_done += batch_records
            self._step_stats.observe_step(step_s, batch_records)
        return {
            "loss_sum": loss_sum,
            "loss_count": loss_count,
            "records_done": records_done,
            "step_time_sum": step_time_sum,
            "interrupted": interrupted,
        }

    def _grouped_stream(self, stream, k, interrupted):
        """THE grouped-dispatch scaffold, shared by the training/eval/
        prediction task paths: yield lists of ready-to-run batches — full
        k-groups, then one trailing partial. Grouped mode (k > 1) buffers
        HOST batches (the wire cast is applied BEFORE _ensure_state so
        every path traces with identical feature dtypes, and the mask leaf
        is exempted by _wire_cast so record accounting stays exact);
        k == 1 yields single prefetched (device-resident, pre-cast)
        batches. On shutdown/preemption `interrupted` (a mutable list) gets
        a True appended and the stream ends at the group boundary — the
        trailing partial is NOT yielded, so drain reports cover whole
        groups only."""
        from elasticdl_tpu.data.prefetch import _wire_cast

        buf = []
        if k == 1:
            stream = self._prefetched(stream)
        else:
            # grouped mode consumes host batches directly (no prefetcher
            # to self-time): attribute each pull to data_wait here
            stream = profile_lib.timed_iter(
                stream, profile_lib.get_profiler()
            )
        for batch in stream:
            if self._shutdown.is_set():
                interrupted.append(True)
                return
            if k > 1:
                batch = _wire_cast(batch, self.cfg.wire_dtype)
            self._ensure_state(batch)
            buf.append(batch)
            if len(buf) == k:
                yield buf
                buf = []
        if buf:
            yield buf

    def _run_training_task_grouped(self, task: pb.Task, k: int) -> Dict[str, float]:
        """--steps_per_dispatch > 1: buffer k host batches, run them as ONE
        XLA dispatch (Trainer.train_many lax.scan). Exactly-once accounting
        is unchanged — a group's records count as applied only after its
        dispatch's loss is read back, and preemption stops at a group
        boundary so the drain report covers whole groups. A trailing partial
        group falls back to single train_steps (two compiled programs total,
        not one per remainder length)."""
        import jax.numpy as jnp

        from elasticdl_tpu.parallel.mesh import shard_batch_stack

        svc = self._data_service(pb.TRAINING)
        stats = {"loss_sum": 0.0, "loss_count": 0, "records_done": 0,
                 "step_time_sum": 0.0, "interrupted": False}
        self._mid_training_task = True
        interrupted: list = []

        for buf in self._grouped_stream(
            svc.batches(task.shard_name, task.start, task.end), k, interrupted
        ):
            self._maybe_profile()
            t0 = time.perf_counter()
            # straggler-injection site (one per GROUP dispatch — see the
            # single-step path for the per-worker addressing rationale)
            faults.fire(f"worker.train_step.{self.worker_id}")
            if len(buf) == k:
                stacked = shard_batch_stack(
                    self._mesh, buf, self._spec.batch_partition)
                self._state, m = self._trainer.train_many(self._state, stacked)
                # one sync per GROUP (k steps), deliberate — it forces the
                # dispatch so step_time covers device compute, and grouped
                # mode amortizes it k-fold: edl-lint: disable=EDL201
                stats["loss_sum"] += float(jnp.sum(m["loss"]))
            else:
                for b in buf:
                    self._state, logs = self._trainer.train_step(self._state, b)
                    # trailing-partial fallback, same rationale as above:
                    # edl-lint: disable=EDL201
                    stats["loss_sum"] += float(logs["loss"])
            group_s = time.perf_counter() - t0
            stats["step_time_sum"] += group_s
            _TRAIN_STEP_S.observe(group_s / max(1, len(buf)))
            # one profile record per group, normalized per step inside
            # step_done (grouped and single-step workers stay comparable)
            profile_lib.get_profiler().add("compute", group_s)
            profile_lib.get_profiler().step_done(len(buf))
            stats["loss_count"] += len(buf)
            self._global_step += len(buf)
            self._model_version += len(buf)
            # per-group record accounting for the drain report:
            # edl-lint: disable=EDL201
            group_records = int(sum(b["mask"].sum() for b in buf))
            stats["records_done"] += group_records
            # one telemetry sample per group, normalized to per-step values
            # so grouped and single-step workers score comparably
            self._step_stats.observe_step(
                group_s / max(1, len(buf)), group_records / max(1, len(buf))
            )
        stats["interrupted"] = bool(interrupted)
        return stats

    def _report_preempted_task(self, task: pb.Task, stats: Dict[str, float]) -> None:
        """Drain protocol for an interrupted training task. Records may only
        be retired from the master's queue when a checkpoint covering them is
        durably on disk, and a drain checkpoint may only survive when its
        retirement report was accepted — otherwise either path loses or
        double-applies records:

          1. save the mid-task state (wait for durability); workers that
             don't checkpoint (worker_id != 0, no checkpoint_dir, failed
             save) report records_processed=0 → the FULL task is requeued,
             retry-free, and nothing is lost;
          2. report the applied-record count;
          3. if the master rejects the report (stale lease — e.g. the task
             timed out and was already requeued whole) or the report can't be
             delivered, delete the just-saved drain checkpoint so a relaunch
             restores the last task-boundary state instead.

        Residual window (documented at-least-once, same as the reference's
        PS mode where pushed gradients survived a task re-run): the process
        dying between (1) and (3) leaves a drain checkpoint whose task is
        re-leased in full.
        """
        mngr = self._checkpoint_manager()
        records_applied = int(stats["records_done"])
        records_done = records_applied
        drain_step = None
        if records_done > 0 and mngr is not None and self.worker_id == 0:
            try:
                drain_step = mngr.save(self._state, wait=True)
            except Exception:
                logger.exception("drain checkpoint failed; requeueing full task")
                drain_step = None
        if drain_step is None:
            records_done = 0
        delivered = False
        try:
            faults.fire("worker.report_task")
            resp = self._stub.ReportTaskResult(
                pb.ReportTaskResultRequest(
                    worker_id=self.worker_id,
                    task_id=task.task_id,
                    success=False,
                    preempted=True,
                    err_message="preempted",
                    records_processed=records_done,
                    loss_sum=stats["loss_sum"],
                    loss_count=int(stats["loss_count"]),
                    model_version=self._model_version,
                ),
                timeout=10,
            )
            accepted = resp.accepted
            delivered = True
        except Exception as e:
            logger.warning("preemption drain report failed to deliver: %s", e)
            accepted = False
            if is_stale_generation(e):
                # generation fence: a DEFINITIVE rejection (the fence aborts
                # before any mutation) — the restarted master replayed our
                # lease back into todo WHOLE, so the full task will re-run
                # and the drain checkpoint (covering a partial span) would
                # double-apply. Same semantics as an explicit rejection,
                # independent of whether the reconnect handshake succeeds.
                delivered = True
                self._maybe_reconnect(e)
        if accepted:
            # Clear the mid-task flag only when the persisted state and the
            # task queue actually agree: either the drain checkpoint covers
            # the applied records, or no records were applied at all. When
            # the save failed (full task requeued), the live state still
            # holds the requeued task's records and must NOT be persisted by
            # the post-loop forced save.
            if drain_step is not None or records_applied == 0:
                self._mid_training_task = False
            if drain_step is not None:
                self._last_ckpt_step = drain_step
        elif drain_step is not None and delivered:
            # Explicit rejection (stale lease): the full task will re-run, so
            # this checkpoint would double-apply — discard it. A DELIVERY
            # failure is ambiguous (the master may have retired the records):
            # keep the checkpoint then, since losing retired records is worse
            # than the bounded double-apply of an undelivered report
            # (at-least-once, like the reference's PS mode).
            mngr.delete(drain_step)

    def _run_evaluation_task(self, task: pb.Task) -> bool:
        """Returns True if interrupted by shutdown/preemption (no report).
        Full k-groups run as ONE eval_many scan (metric states are the
        carry — numerically equivalent to sequential steps); the scaffold
        (wire cast, buffering, prefetch selection) is _grouped_stream."""
        from elasticdl_tpu.parallel.mesh import shard_batch_stack

        svc = self._data_service(pb.EVALUATION)
        states = self._trainer.new_metric_states()
        k = max(1, self.cfg.steps_per_dispatch)
        interrupted: list = []

        for buf in self._grouped_stream(
            svc.batches(task.shard_name, task.start, task.end), k, interrupted
        ):
            if len(buf) == k and k > 1:
                states = self._trainer.eval_many(
                    self._state,
                    shard_batch_stack(
                        self._mesh, buf, self._spec.batch_partition),
                    states,
                )
            else:
                for b in buf:
                    states = self._trainer.eval_step(self._state, b, states)
        if interrupted:
            return True
        import jax

        msg = pb.ReportEvaluationMetricsRequest(
            worker_id=self.worker_id,
            eval_job_id=task.eval_job_id,
            task_id=task.task_id,
        )
        for name, state in states.items():
            arr = np.asarray(jax.device_get(state), np.float32)
            msg.states.append(pb.MetricState(name=name, data=arr.tobytes()))
        self._stub.ReportEvaluationMetrics(msg, timeout=30)
        return False

    def _run_prediction_task(self, task: pb.Task) -> bool:
        """Returns True if interrupted by shutdown/preemption (no report).
        Full k-groups run as one predict_many dispatch (outputs come back
        stacked, fed to the processor per batch in order); the scaffold is
        _grouped_stream."""
        import jax

        from elasticdl_tpu.parallel.mesh import shard_batch_stack
        from elasticdl_tpu.worker.prediction_outputs_processor import (
            iter_stacked,
            mask_predictions,
        )

        svc = self._data_service(pb.PREDICTION)
        processor = self._spec.prediction_outputs_processor
        k = max(1, self.cfg.steps_per_dispatch)
        interrupted: list = []

        def process(batch, outputs):
            if processor is None:
                return
            valid = np.asarray(batch["mask"]) > 0
            # pytree-safe: predict outputs may be a dict/tuple, not an array
            processor.process(
                mask_predictions(jax.device_get(outputs), valid),
                self.worker_id,
            )

        for buf in self._grouped_stream(
            svc.batches(task.shard_name, task.start, task.end), k, interrupted
        ):
            if len(buf) == k and k > 1:
                stacked = shard_batch_stack(
                    self._mesh, buf, self._spec.batch_partition)
                outs_dev = self._trainer.predict_many(self._state, stacked)
                if processor is not None:
                    # D2H only when someone consumes the outputs
                    for b, out in zip(buf, iter_stacked(outs_dev, len(buf))):
                        process(b, out)
            else:
                for b in buf:
                    process(b, self._trainer.predict_step(self._state, b))
        return bool(interrupted)

    # ------------------------------------------------------------------ #

    def _init_embedding_tier(self) -> None:
        """Join the elastic embedding tier (cfg.embedding_shards > 0):
        register this worker's owning store, build the pull/push client
        off the master's shard map, install any shards the map (or a
        checkpoint) assigns here. Best-effort at boot — a worker that
        cannot join the tier can still train dense models; models that
        NEED tier tables fail loudly at pull time instead."""
        if self.cfg.embedding_shards <= 0 or self._tier is not None:
            return
        try:
            from elasticdl_tpu.embedding.tier import WorkerTierRuntime

            transport = bind_servicer = None
            if (self.cfg.embedding_transport == "grpc"
                    and getattr(self, "_data_server", None) is not None):
                # the partition-tolerant data plane (ISSUE 15): route
                # peers' shards over gRPC through the robustness layer;
                # our own store short-circuits in-process
                from elasticdl_tpu.embedding.data_plane import (
                    GrpcTransport,
                    ResilientTransport,
                    default_policies,
                )

                budget_s = self.cfg.embedding_rpc_deadline_ms / 1e3
                queue_journal = ""
                if (self.cfg.embedding_push_queue > 0
                        and self.cfg.checkpoint_dir):
                    queue_journal = os.path.join(
                        self.cfg.checkpoint_dir,
                        f"emb-push-queue-{self.worker_id}.jsonl")
                transport = ResilientTransport(
                    GrpcTransport(default_timeout_s=budget_s,
                                  shm=self.cfg.embedding_shm),
                    policies=default_policies(budget_s),
                    staleness_bound=self.cfg.embedding_cache_staleness,
                    hedge=self.cfg.embedding_hedge_ms >= 0,
                    hedge_delay_ms=max(0, self.cfg.embedding_hedge_ms),
                    queue_journal=queue_journal,
                    queue_max=self.cfg.embedding_push_queue,
                )
                bind_servicer = self._data_server.servicer
            self._tier = WorkerTierRuntime(
                self._stub, self.worker_id,
                checkpoint_dir=self.cfg.checkpoint_dir,
                transport=transport,
                bind_servicer=bind_servicer,
                cache_rows=self.cfg.embedding_cache_rows,
                cache_staleness=self.cfg.embedding_cache_staleness,
                read_replicas=self.cfg.embedding_read_replicas > 0,
                pipeline_depth=self.cfg.embedding_pull_pipeline,
            )
            logger.info(
                "joined embedding tier: map v%d, %d shard(s) resident",
                self._tier.client.view.version,
                len(self._tier.store.resident_shards()),
            )
        except Exception:
            logger.exception(
                "embedding tier init failed; tier disabled for this worker"
            )

    def run(self) -> int:
        self._connect()
        self._init_embedding_tier()
        # /metrics + /healthz for this worker (best-effort, off the hot
        # path; a set EDL_METRICS_PORT overrides cfg.metrics_port either
        # way, -1/off in either disables)
        from elasticdl_tpu.observability.http import start_server

        self._metrics_server = start_server(
            role=f"worker-{self.worker_id}", port=self.cfg.metrics_port
        )
        self._build_trainer()
        self._heartbeat_thread = threading.Thread(
            target=self._heartbeat_loop, daemon=True
        )
        self._heartbeat_thread.start()

        tasks_done = 0
        wait_backoff = 1.0
        while not self._shutdown.is_set():
            if self._lease_queue:
                # drain locally held leases before re-polling (batched
                # leases: N tasks per GetTask round-trip)
                task = self._lease_queue.popleft()
            else:
                try:
                    resp = self._stub.GetTask(
                        pb.GetTaskRequest(
                            worker_id=self.worker_id,
                            max_tasks=self.cfg.task_lease_batch,
                        ),
                        timeout=30,
                    )
                except Exception as e:
                    logger.warning("get_task failed: %s; retrying", e)
                    if self._maybe_reconnect(e):
                        # master restarted: the handshake landed, re-lease
                        # immediately under the new generation
                        continue
                    if self._master_unreachable():
                        break
                    # jittered: a cohort of relaunched workers retrying a
                    # recovering master on the same constant beat is a
                    # thundering herd (edl-lint EDL304). Goodput: time
                    # spent riding out an unreachable master is the
                    # `reconnect` category.
                    with goodput_lib.get_ledger().phase("reconnect"):
                        time.sleep(jittered(2))
                    continue
                if resp.job_done:
                    logger.info("job done after %d tasks", tasks_done)
                    self._job_done = True
                    break
                # an old master never fills `tasks`; fall back to the
                # classic singular field (WAIT only ever arrives alone)
                leased = list(resp.tasks) or [resp.task]
                task = leased[0]
                self._lease_queue.extend(leased[1:])
                wait_backoff = resp.backoff_seconds or 1.0
            pending_lr, self._pending_lr = self._pending_lr, None
            if pending_lr is not None and self._state is not None:
                from elasticdl_tpu.training.lr_modulation import (
                    apply_learning_rate,
                )

                self._state = apply_learning_rate(
                    self._trainer, self._state, pending_lr
                )
                logger.info("runtime LR set to %.6g", pending_lr)
            elif pending_lr is not None:
                # state not built yet: keep it pending for the next loop
                self._pending_lr = pending_lr
            if self._ckpt_requested and not self._mid_training_task:
                # master-requested checkpoint (heartbeat should_checkpoint),
                # taken at a task boundary only
                self._ckpt_requested = False
                try:
                    self._maybe_checkpoint(force=True)
                except Exception:
                    logger.exception("master-requested checkpoint failed")
            if self._pending_rescale is not None:
                # planned in-place rescale at a clean task boundary: live
                # handoff + executable-cache reuse, no teardown (the
                # pending target is consumed either way — no retry loop)
                try:
                    with profile_lib.get_profiler().phase("handoff"):
                        self._rescale_in_place()
                except Exception:
                    logger.exception("in-place rescale failed; mesh kept")
            if self._tier is not None and self._tier_refresh_pending:
                # resharding reaction at a clean task boundary: refetch
                # the map, promote/install newly-owned shards (replica
                # promotion first — see WorkerTierRuntime), confirm the
                # moves, adopt new replica assignments
                self._tier_refresh_pending = False
                try:
                    self._tier.on_world_change()
                except Exception:
                    logger.exception("embedding tier refresh failed")
            elif self._tier is not None:
                # replica delta sync rides the task boundary (cheap
                # no-op when this worker replicates nothing): replicas
                # stay within the staleness bound of their primaries
                # without a dedicated thread contending with the step
                try:
                    self._tier.sync_replicas()
                except Exception:
                    logger.exception("embedding replica sync failed")
            if task.type == pb.WAIT:
                # jittered so an idle swarm does not re-poll in phase
                # (epoch boundaries unblock every worker at once).
                # Goodput: idle-with-no-task is the `lease_wait` category
                # — the autoscaler's shrink signal.
                with goodput_lib.get_ledger().phase("lease_wait"):
                    time.sleep(jittered(wait_backoff))
                continue

            report = pb.ReportTaskResultRequest(
                worker_id=self.worker_id, task_id=task.task_id, success=True
            )
            try:
                if task.type == pb.TRAINING:
                    stats = self._run_training_task(task)
                    _TRAIN_STEPS.inc(int(stats["loss_count"]))
                    _TRAIN_RECORDS.inc(int(stats["records_done"]))
                    if stats["step_time_sum"] > 0:
                        _TRAIN_THROUGHPUT.set(
                            stats["records_done"] / stats["step_time_sum"]
                        )
                    if stats["interrupted"]:
                        self._report_preempted_task(task, stats)
                        break
                    report.loss_sum = stats["loss_sum"]
                    report.loss_count = int(stats["loss_count"])
                    report.step_time_sum = stats["step_time_sum"]
                    report.step_count = int(stats["loss_count"])
                elif task.type == pb.EVALUATION:
                    if self._run_evaluation_task(task):
                        break
                elif task.type == pb.PREDICTION:
                    if self._run_prediction_task(task):
                        break
                elif task.type == pb.SAVE_MODEL:
                    self._save_checkpoint()
                report.records_processed = task.end - task.start
                if self._state is not None:
                    report.model_version = self._model_version
            except Exception as e:
                logger.exception("task %d failed", task.task_id)
                report.success = False
                report.err_message = str(e)[:512]
            try:
                faults.fire("worker.report_task")
                self._stub.ReportTaskResult(report, timeout=30)
                if task.type == pb.TRAINING and report.success:
                    # state and task queue agree here: safe checkpoint point
                    self._mid_training_task = False
                    self._maybe_checkpoint()
            except Exception as e:
                logger.warning("report failed for task %d: %s", task.task_id, e)
                if self._maybe_reconnect(e):
                    # fenced report from before the crash: the restarted
                    # master requeued this lease, so the task re-runs and
                    # retires exactly once there — never resend the report
                    # under the new generation (that WOULD double-count)
                    logger.warning(
                        "task %d report was fenced by the restarted master; "
                        "the requeued lease re-runs it", task.task_id,
                    )
            tasks_done += 1

        # A trace window still open at exit (short job / preemption) must be
        # flushed — an unstopped trace writes nothing.
        self._stop_profiler()

        # Preemption-triggered save (reference: preemption checkpoints in
        # the checkpoint service): SIGTERM'd workers persist progress so the
        # relaunch resumes instead of retraining.
        if self._preempted:
            try:
                self._maybe_checkpoint(force=True)
            except Exception:
                logger.exception("preemption checkpoint failed")
            # the last seconds before a preemption exit are exactly what a
            # postmortem wants: cut the black box here (explicit trigger)
            flight_lib.get_recorder().dump("preempt")

        # Export runs here, not in the GetTask branch: a worker may learn the
        # job finished from the heartbeat shutdown flag (another worker took
        # the last task) without ever seeing a job_done GetTask response.
        if self._job_done and not self._preempted:
            self._export_final_model()

        processor = self._spec.prediction_outputs_processor if self._spec else None
        if processor is not None:
            try:
                processor.close()
            except Exception:
                logger.exception("prediction outputs processor close failed")

        # Orderly teardown: stop the heartbeat thread and close the channel
        # BEFORE interpreter exit — a grpc call in flight during shutdown
        # aborts the process from the C++ layer.
        self._shutdown.set()
        if getattr(self, "_metrics_server", None) is not None:
            try:
                self._metrics_server.stop()
            except Exception:
                logger.debug("metrics endpoint stop failed", exc_info=True)
        if getattr(self, "_data_server", None) is not None:
            try:
                self._data_server.stop()
            except Exception:
                logger.debug("data-plane endpoint stop failed",
                             exc_info=True)
        # flush trace.jsonl durably (the tracer reopens on reconfigure)
        tracing.get_tracer().close()
        if self._heartbeat_thread is not None:
            self._heartbeat_thread.join(timeout=2 * self.cfg.worker_heartbeat_s)
        try:
            self._channel.close()
        except Exception:
            # teardown-only: the process is exiting either way, but the
            # failure is still worth a debug line for post-mortems
            logger.debug("grpc channel close failed at exit", exc_info=True)
        # A preempted worker exits non-zero (EX_TEMPFAIL) so the instance
        # manager relaunches it and recovers its lease immediately; clean
        # job-done exits return 0. A lost master is also EX_TEMPFAIL: under
        # a live manager that means relaunch; orphaned, it frees the process.
        return 75 if (self._preempted or self._master_lost) else 0

    def _export_final_model(self) -> None:
        """Job-end serving export (reference: model_handler → SavedModel at
        job completion). Worker 0 writes `--output`; sharded tables gather
        through device_get inside export_model."""
        if not self.cfg.output or self.worker_id != 0 or self._state is None:
            return
        try:
            from elasticdl_tpu.training.export import export_model

            export_model(
                self._state,
                self.cfg.output,
                model_def=self.cfg.model_def,
                model_params=self._spec.model_params,
                module_name=self._spec.module_name,
            )
        except Exception:
            logger.exception("final model export failed")

    def preempt(self) -> None:
        """SIGTERM hook: finish/abandon the current batch, checkpoint, exit."""
        logger.info("preemption signal received; draining")
        self._preempted = True
        self._shutdown.set()

    def _save_checkpoint(self) -> None:
        """Serve a SAVE_MODEL task: persist current state, wait for
        durability. With no live state (a relaunched worker that has not
        processed a batch yet), success is only reported if a checkpoint
        already exists on disk — that checkpoint IS the current state, since
        no training happened since restore. Otherwise fail the task so the
        dispatcher retries it on a worker that has state (silent success
        here would retire the job's durability task with nothing saved)."""
        mngr = self._checkpoint_manager()
        if mngr is None:
            # A SAVE_MODEL task with no checkpoint_dir cannot persist
            # anything; silent success would retire the job's durability
            # task with nothing saved. Fail loudly — the dispatcher's
            # bounded retries (max_task_retries) then fail it permanently.
            raise RuntimeError(
                "SAVE_MODEL: no checkpoint_dir configured, nothing to save to"
            )
        if self._state is None:
            if mngr.latest_step(refresh=True) is None:
                raise RuntimeError(
                    "SAVE_MODEL: no live training state and no checkpoint on "
                    "disk to vouch for"
                )
            return
        mngr.save(self._state, wait=True)
        self._last_ckpt_step = self._state.model_version
