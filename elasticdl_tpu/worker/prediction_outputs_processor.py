"""Prediction-output hooks for prediction jobs.

Reference parity: elasticdl/python/worker/prediction_outputs_processor.py —
`BasePredictionOutputsProcessor.process(predictions, worker_id)` is invoked by
the worker with each minibatch of prediction outputs. Users subclass it in
their model-zoo module and expose it via a module-level
`prediction_outputs_processor()` factory (see ModelSpec.from_config).
"""

from __future__ import annotations

import os
from typing import Any, List

import numpy as np


def mask_predictions(outputs: Any, valid: np.ndarray) -> Any:
    """Drop padding rows from prediction outputs of ANY pytree shape.

    `predict_step` returns whatever `model.apply` returns — an array, a
    dict, a tuple, any pytree whose leaves share the batch leading dim.
    Every leaf gets `np.asarray(leaf)[valid]`; a plain array comes back a
    plain array, so existing array-output models are unchanged.
    """
    import jax

    return jax.tree_util.tree_map(
        lambda leaf: np.asarray(leaf)[valid], outputs
    )


def iter_stacked(outputs: Any, k: int):
    """Yield the k per-batch pytrees out of a stacked `predict_many`
    result (leaves have a leading group dim of k). Works for plain
    arrays and arbitrary pytrees alike."""
    import jax

    leaves = jax.device_get(outputs)
    for i in range(k):
        yield jax.tree_util.tree_map(lambda leaf: np.asarray(leaf)[i], leaves)


class BasePredictionOutputsProcessor:
    """Subclass and override `process`. The default is a no-op."""

    def process(self, predictions: Any, worker_id: int) -> None:
        """Called once per prediction minibatch with host-numpy outputs
        (padding rows already removed)."""

    def close(self) -> None:
        """Called once when the worker finishes its prediction tasks."""


class InMemoryPredictionOutputsProcessor(BasePredictionOutputsProcessor):
    """Accumulates all outputs in memory — tests and small jobs."""

    def __init__(self) -> None:
        self.outputs: List[np.ndarray] = []

    def process(self, predictions: Any, worker_id: int) -> None:
        self.outputs.append(np.asarray(predictions))

    def result(self) -> np.ndarray:
        return (
            np.concatenate(self.outputs, axis=0)
            if self.outputs
            else np.empty((0,), np.float32)
        )


class NpyPredictionOutputsProcessor(BasePredictionOutputsProcessor):
    """Streams outputs to `<out_dir>/predictions_worker<id>_p<pid>_<n>.npy`,
    one file per minibatch — per-worker files never contend (the reference's
    processors wrote per-worker ODPS partitions for the same reason). The pid
    component keeps a relaunched worker (same worker_id, fresh counter) from
    overwriting files its previous incarnation already wrote."""

    def __init__(self, out_dir: str) -> None:
        self.out_dir = os.path.abspath(out_dir)
        self._n = 0
        self._made_dir = False  # deferred: ModelSpec constructs processors
        # for every job type, and a training job must not mkdir as a side
        # effect (or crash in a read-only cwd)

    def process(self, predictions: Any, worker_id: int) -> None:
        if not self._made_dir:
            os.makedirs(self.out_dir, exist_ok=True)
            self._made_dir = True
        path = os.path.join(
            self.out_dir,
            f"predictions_worker{worker_id}_p{os.getpid()}_{self._n:06d}.npy",
        )
        np.save(path, np.asarray(predictions))
        self._n += 1
