"""Multi-process SPMD worker cohort: one logical worker, one global mesh.

Reference parity: the reference's elastic-AllReduce mode (SURVEY §3.4) — N
worker pods formed one Horovod ring, each trained on its own minibatches,
gradients averaged collectively. Rebuilt TPU-native: N processes initialize
ONE `jax.distributed` world and ONE mesh over all their devices; every
process executes the same jitted train step (SPMD), each feeding its
process-local rows of the global batch; gradient averaging is the `psum`
XLA inserts over the `data` axis.

Topology of control: process 0 (the leader) is the only one the master
sees — it leases tasks, reports results, and heartbeats. Followers receive
a small broadcast control vector per task (op, shard, span, flags) and run
the identical data/compute sequence. Every collective (train step, eval,
checkpoint save/restore, export gather) is executed by ALL processes; all
host-side decisions ride the control broadcast, so the cohort stays in
lockstep by construction.

Elasticity = cohort re-formation (SURVEY §7 hard-part 1): any member dying
makes the coordination service fail the others; the whole cohort exits and
the process manager relaunches it; the new world restores from the latest
checkpoint and re-leases at the task boundary.

SIGTERM (planned preemption): a FOLLOWER exits immediately (EX_TEMPFAIL) —
it cannot drain, because the leader would keep broadcasting control vectors
it no longer answers. The LEADER, however, drains collectively: it finishes
the in-flight task, then broadcasts OP_ABORT|FLAG_CHECKPOINT so every
process joins one final collective save before exiting EX_TEMPFAIL — the
relaunched cohort restores at the pre-kill step, so a planned preemption
redoes at most the records of one partially-reported task instead of
`steps_per_dispatch x checkpoint_steps` worth of work (see
`request_preempt`).
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from elasticdl_tpu.common import membership_signal
from elasticdl_tpu.common.config import JobConfig
from elasticdl_tpu.common.constants import ExitCode
from elasticdl_tpu.common.log_utils import default_logger
from elasticdl_tpu.data.reader import create_data_reader
from elasticdl_tpu.observability import flight as flight_lib
from elasticdl_tpu.observability import goodput as goodput_lib
from elasticdl_tpu.observability import profile as profile_lib
from elasticdl_tpu.observability import reqtrace as reqtrace_lib
from elasticdl_tpu.observability.health import (
    STATS_METADATA_KEY,
    WorkerStepStats,
    encode_stats,
)
from elasticdl_tpu.parallel.elastic import (
    CohortContext,
    context_from_env,
    make_global_batch,
    make_global_batch_stack,
)
from elasticdl_tpu.proto import elasticdl_tpu_pb2 as pb
from elasticdl_tpu.proto.service import (
    RetryingMasterStub,
    is_stale_generation,
    jittered,
    make_channel,
    register_with_retry,
    reregister,
)
from elasticdl_tpu.training.model_spec import ModelSpec
from elasticdl_tpu.worker.task_data_service import TaskDataService

logger = default_logger(__name__)

# control vector:
#   [op, task_id, task_type, shard_idx, start, end, flags, eval_job, lr_bits]
# lr_bits = float64 bit-pattern of the master-pushed LR override (0 = none);
# riding the broadcast keeps every process applying the same LR at the same
# task boundary (SPMD lockstep).
OP_NOOP, OP_TASK, OP_DONE, OP_ABORT = 0, 1, 2, 3
FLAG_CHECKPOINT = 1
CTRL_LEN = 9


def _lr_to_bits(lr: float) -> int:
    return 0 if not lr else int(np.float64(lr).view(np.int64))


def _bits_to_lr(bits: int) -> float:
    return 0.0 if not bits else float(np.int64(bits).view(np.float64))



class CohortWorker:
    def __init__(self, cfg: JobConfig, ctx: Optional[CohortContext] = None):
        self.cfg = cfg
        self.ctx = ctx or context_from_env(cfg)
        self._stub: Optional[RetryingMasterStub] = None
        self._trainer = None
        self._state = None
        self._spec: Optional[ModelSpec] = None
        self._mesh = None
        self._services: Dict[int, TaskDataService] = {}
        self._shards: Dict[int, List[Tuple[str, int, int]]] = {}
        self._ckpt_manager = None
        self._last_ckpt_step = 0
        self._shutdown = threading.Event()
        self._job_done = False
        self._ckpt_requested = False  # heartbeat should_checkpoint bit
        self._preempt = False         # leader: SIGTERM drain requested
        self._last_master_ok = time.monotonic()  # leader: last successful RPC
        self._master_lost = False
        # Plain-int mirror of state.model_version for the heartbeat thread:
        # int(state.step) blocks on the in-flight donated computation (see
        # worker.py's identically-named field), which would stall heartbeats
        # for the length of a dispatch.
        self._model_version = 0
        self._pushed_lr = 0.0         # leader: last LR override from heartbeat
        self._ctrl_pushed_lr = 0.0    # all: latest override from the ctrl vector
        self._applied_push_lr = 0.0   # all: last override applied to state
        # rescale fast path: first trained host batch (the speculative
        # compiler's example input) + the background compiler itself
        self._example_host_batch = None
        self._spec_compiler = None
        self.worker_id = -1
        self._name = ""               # set at leader registration
        # cohort-aggregated membership: master-assigned ids for this
        # cohort's member processes 1..N-1 (leader only; empty for
        # single-process worlds). Their beats ride the leader's single
        # Heartbeat as MemberBeat entries.
        self._member_ids: List[int] = []
        # batched leases (--task_lease_batch): leases still to broadcast,
        # drained before the next GetTask poll; cleared on reconnect
        self._lease_queue: "deque" = deque()
        # heartbeat telemetry (observability/health.py): every process —
        # leader AND followers — keeps its own step-stats window now
        # (followers force their local view of each collective dispatch),
        # exchanged to the leader over the cohort's collective channel
        # (allgather_ints) so MemberBeats carry REAL follower step times
        self._step_stats = WorkerStepStats()
        self._phase = "boot"          # boot -> train/idle (leader payload)
        # leader: latest follower-local stats rows by process index
        # (written by the task loop at the post-task exchange, read by the
        # heartbeat thread — whole-dict swaps only, so no lock needed)
        self._member_stats: Dict[int, Dict[str, Any]] = {}
        # elastic embedding tier (cfg.embedding_shards > 0): leader-owned
        # store + client; shards drain to checkpoint at teardown and the
        # next generation's leader restores them (_init_embedding_tier)
        self._tier = None

    # ------------------------------------------------------------------ #
    # setup (identical on every process)

    def _build(self) -> None:
        import jax

        from elasticdl_tpu.common.runtime import configure_jax_runtime
        from elasticdl_tpu.parallel.mesh import build_job_mesh
        from elasticdl_tpu.training.trainer import Trainer

        configure_jax_runtime(self.cfg)
        self._spec = ModelSpec.from_config(self.cfg)
        self._mesh = build_job_mesh(self.cfg, jax.devices())
        from elasticdl_tpu.training import compile_cache as cc

        # config-derived token: a re-formed generation at the same mesh
        # shape (and the speculative compiler's neighbor trainers) share
        # executables instead of re-tracing
        self._trainer = Trainer(
            self._spec, self._mesh, remat=self.cfg.remat, remat_policy=self.cfg.remat_policy,
            grad_accum=self.cfg.grad_accum_steps,
            seed=self.cfg.shuffle_seed,
            cache_token=cc.job_cache_token(self.cfg),
        )

    def _data_service(self, task_type: int) -> TaskDataService:
        if task_type not in self._services:
            paths = {
                pb.TRAINING: self.cfg.training_data,
                pb.EVALUATION: self.cfg.validation_data or self.cfg.training_data,
                pb.PREDICTION: self.cfg.prediction_data,
            }
            mode = {
                pb.TRAINING: "training",
                pb.EVALUATION: "evaluation",
                pb.PREDICTION: "prediction",
            }[task_type]
            reader = create_data_reader(
                paths[task_type], self.cfg.data_reader,
                **self.cfg.data_reader_params,
            )
            parse = self._spec.dataset_fn(mode, reader.metadata)
            from elasticdl_tpu.parallel.mesh import data_axis

            multiple = dict(
                zip(self._mesh.axis_names, self._mesh.devices.shape)
            )[data_axis(self._mesh)]
            self._services[task_type] = TaskDataService(
                reader, parse, self.cfg.minibatch_size, batch_multiple=multiple
            )
            # shard index -> name map; identical everywhere (sorted) so a
            # broadcast int addresses the same shard on every process
            self._shards[task_type] = sorted(reader.create_shards())
        return self._services[task_type]

    def _shard_name(self, task_type: int, shard_idx: int) -> str:
        self._data_service(task_type)
        return self._shards[task_type][shard_idx][0]

    def _shard_index(self, task_type: int, name: str) -> int:
        self._data_service(task_type)
        for i, (n, _, _) in enumerate(self._shards[task_type]):
            if n == name:
                return i
        raise KeyError(f"unknown shard {name!r}")

    def _checkpoint_manager(self):
        if self._ckpt_manager is None and self.cfg.checkpoint_dir:
            from elasticdl_tpu.training.checkpoint import CheckpointManager

            self._ckpt_manager = CheckpointManager(
                self.cfg.checkpoint_dir, keep=self.cfg.keep_checkpoint_max
            )
        return self._ckpt_manager

    def _ensure_state(self, example_batch) -> None:
        if self._state is not None:
            return
        self._state = self._trainer.init_state(example_batch)
        mngr = self._checkpoint_manager()
        if mngr is not None and mngr.latest_step() is not None:
            restored = mngr.restore(self._state)
            if restored is not None:
                self._state = restored
                self._last_ckpt_step = self._state.model_version
                logger.info(
                    "cohort resumed from checkpoint at step %d",
                    self._last_ckpt_step,
                )
        self._model_version = self._state.model_version
        if self.ctx.num_processes != self.cfg.num_processes:
            # Dynamic resizing does NOT change the effective global batch in
            # cohort mode: every generation consumes the same
            # cfg.minibatch_size rows per step (make_global_batch hands each
            # device a slice of one identical host batch), so the linear
            # LR-scaling rule does not apply — only per-device slice size
            # changed. This differs from independent (non-cohort) workers,
            # where worker count multiplies the global batch and
            # worker.py DOES rescale via lr_modulation.linear_scale.
            logger.info(
                "cohort world resized %d -> %d processes; global batch and "
                "LR unchanged (strong scaling)",
                self.cfg.num_processes, self.ctx.num_processes,
            )

    # ------------------------------------------------------------------ #
    # leader-only: master RPCs

    def _connect(self) -> None:
        import os
        import socket

        self._channel = make_channel(self.cfg.master_addr)
        # Hardened stub (deadlines, idempotent retries, circuit breaker);
        # every successful RPC refreshes the master-unreachable clock. The
        # channel_factory bounds master-restart recovery: repeated wire
        # failures rebuild the channel rather than trusting a wedged one.
        self._stub = RetryingMasterStub(
            self._channel, on_success=self._note_master_ok,
            channel_factory=lambda: make_channel(self.cfg.master_addr),
        )
        # Boot registration rides out a master that is down or restarting
        # (proto/service.py's register_with_retry, shared with worker.py):
        # the leader is always worker 0, so retries carry the REREGISTER
        # marker and a successor master treats them as an idempotent
        # reconnect of the journaled member, not a ghost second join.
        # registered once, reused by every reconnect handshake: a renamed
        # re-register would silently overwrite the membership entry's name
        self._name = f"cohort-{socket.gethostname()}:{os.getpid()}"
        resp = register_with_retry(
            self._stub,
            name=self._name,
            preferred_id=0,
            window_s=self.cfg.master_unreachable_timeout_s,
            shutdown=self._shutdown,
            what="cohort leader",
            # cohort-aggregated membership: member processes join in the
            # SAME round-trip as telemetry entities — the master's fleet
            # view is per-process while reap/version stay per-cohort
            member_names=self._member_names(),
        )
        self.worker_id = resp.worker_id
        self._member_ids = list(resp.member_ids)
        logger.info(
            "cohort leader registered as worker %d (%d processes, %d devices"
            ", %d member entries)",
            self.worker_id, self.ctx.num_processes,
            len(__import__("jax").devices()), len(self._member_ids),
        )

    def _member_names(self) -> List[str]:
        """Stable per-process member identities (processes 1..N-1; the
        leader itself IS the cohort's logical worker entry). Stable across
        reconnects so a restarted master's register_members is idempotent."""
        return [
            f"{self._name}#p{i}" for i in range(1, self.ctx.num_processes)
        ]

    def _note_master_ok(self) -> None:
        self._last_master_ok = time.monotonic()

    def _init_embedding_tier(self) -> None:
        """Leader-only tier membership (cfg.embedding_shards > 0): the
        cohort is ONE logical worker, so the leader owns its shard set.
        Unlike the single-process worker there is no in-place refresh
        path — a cohort rides every world change through teardown +
        re-form (process_manager), and each generation's leader re-joins
        here, restoring its shards from the drain checkpoint."""
        if self.cfg.embedding_shards <= 0 or self._tier is not None:
            return
        try:
            from elasticdl_tpu.embedding.tier import WorkerTierRuntime

            self._tier = WorkerTierRuntime(
                self._stub, self.worker_id,
                checkpoint_dir=self.cfg.checkpoint_dir,
                cache_rows=self.cfg.embedding_cache_rows,
                cache_staleness=self.cfg.embedding_cache_staleness,
                read_replicas=self.cfg.embedding_read_replicas > 0,
                pipeline_depth=self.cfg.embedding_pull_pipeline,
            )
            logger.info(
                "cohort leader joined embedding tier: map v%d, %d "
                "shard(s) resident", self._tier.client.view.version,
                len(self._tier.store.resident_shards()),
            )
        except Exception:
            logger.exception(
                "embedding tier init failed; tier disabled for this cohort"
            )

    def _drain_embedding_tier(self) -> None:
        """The tier half of the cohort's drain: persist resident shards
        (rows + exactly-once watermarks) so the next generation's leader
        restores them bit-exactly."""
        if self._tier is None:
            return
        try:
            self._tier.drain()
        except Exception:
            logger.exception("embedding tier drain failed")

    def _reregister(self) -> None:
        """Leader-only reconnect handshake after a master restart (shared
        with worker.py — proto/service.py's reregister). The cohort itself
        keeps running throughout — only the leader's control-plane session
        is re-established; followers never notice."""
        resp = reregister(
            self._stub, name=self._name, worker_id=self.worker_id,
            member_names=self._member_names(),
        )
        # the restarted master's replay requeued every lease whole — drop
        # the local queue; fresh leases re-run the tasks exactly once
        self._lease_queue.clear()
        self.worker_id = resp.worker_id
        self._member_ids = list(resp.member_ids)
        logger.warning(
            "cohort leader re-registered with restarted master as worker %d; "
            "resuming leases under the new generation", self.worker_id,
        )

    def _maybe_reconnect(self, e: BaseException) -> bool:
        """True when `e` was the stale-generation fence and the reconnect
        handshake ran — the caller retries instead of aborting the cohort."""
        if self.worker_id < 0 or not is_stale_generation(e):
            return False
        try:
            self._reregister()
            return True
        except Exception as handshake_err:
            logger.warning(
                "cohort re-register after master restart failed: %s",
                handshake_err,
            )
            self._master_unreachable()
            return False

    def _master_unreachable(self) -> bool:
        """Leader-only, from RPC-failure paths: True (and flips the
        shutdown that turns the next control vector into OP_ABORT, taking
        the WHOLE cohort down EX_TEMPFAIL) when no master RPC has succeeded
        for master_unreachable_timeout_s. Without this a cohort whose
        master's process tree died keeps spinning on a dead address forever
        — observed as orphan worker processes surviving for hours."""
        limit = self.cfg.master_unreachable_timeout_s
        if limit <= 0 or time.monotonic() - self._last_master_ok < limit:
            return False
        if not self._master_lost:
            self._master_lost = True
            logger.error(
                "no successful master RPC for %.0fs (limit %.0fs): master "
                "presumed gone, aborting cohort (EX_TEMPFAIL)",
                time.monotonic() - self._last_master_ok, limit,
            )
            self._shutdown.set()
        return True

    def _stats_payload(self):
        """Leader heartbeat telemetry (the cohort's collective cadence as
        seen from the leader's dispatch clock)."""
        from elasticdl_tpu.observability import tracing

        stats = self._step_stats.snapshot()
        stats.update(
            phase=self._phase,
            breaker_open=int(bool(self._stub and self._stub.breaker.is_open)),
            num_processes=self.ctx.num_processes,
            world_version=tracing.get_tracer().world_version,
        )
        # per-step phase breakdown + memory watermarks (the leader's own;
        # follower profiles ride their MemberBeats via the exchange)
        stats.update(profile_lib.get_profiler().snapshot())
        # goodput ledger ride-along (ISSUE 12): the leader's own
        # wall-clock attribution (followers' ledgers stay process-local;
        # their training phases ride the member-stats exchange)
        stats.update(goodput_lib.get_ledger().payload())
        # request-diary ride-along (ISSUE 19): the leader's own tail
        # attribution (rt_* keys) + degraded/shm-fallback shares
        stats.update(reqtrace_lib.get_recorder().payload())
        # embedding-tier skew ride-along (ISSUE 11; see worker.py's
        # _stats_payload) — best-effort, never costs the heartbeat
        if self._tier is not None:
            try:
                stats.update(self._tier.client.tier_stats())
            except Exception:
                # edl-lint: disable=EDL303
                pass
        return stats

    def _member_beats(self) -> List[pb.MemberBeat]:
        """Coalesced per-member beats riding the leader's ONE heartbeat
        (cohort-aggregated membership). Each member entry carries that
        FOLLOWER's OWN step telemetry when the post-task collective
        exchange (`_exchange_member_stats`, over the cohort's existing
        broadcast/allgather channel) has delivered a row — real follower
        step times, per-host data-wait/h2d/compute attribution included —
        and falls back to the leader's collective cadence for a follower
        no exchange has covered yet (a just-reformed world). Fleet-scale
        telemetry still costs O(cohorts) RPCs; only the in-cohort channel
        moved, and it rides collectives the task boundary already pays."""
        if not self._member_ids:
            return []
        base = self._step_stats.snapshot()
        member_stats = self._member_stats   # whole-dict snapshot (atomic)
        beats = []
        for idx, mid in enumerate(self._member_ids, start=1):
            row = member_stats.get(idx)
            if row is not None:
                stats = dict(row)
                stats["source"] = "follower-local"
            else:
                stats = dict(base)
                stats["source"] = "leader-coalesced"
            stats.update(phase=self._phase, process_index=idx)
            beats.append(pb.MemberBeat(
                worker_id=mid,
                model_version=self._model_version,
                stats_json=encode_stats(stats),
            ))
        return beats

    #: fields of the fixed-width int64 exchange row, in wire order (times
    #: in microseconds, rates in milli-units — integers survive the int64
    #: channel exactly; floats would need a bit-pattern dance)
    _EXCHANGE_FIELDS = (
        "steps", "step_p50_us", "step_p90_us", "step_max_us",
        "records_per_s_milli", "phase_data_wait_us", "phase_h2d_us",
        "phase_compute_us",
    )

    def _exchange_row(self) -> List[int]:
        """This process's stats as the fixed-width integer row."""
        snap = self._step_stats.snapshot()
        prof = profile_lib.get_profiler().snapshot(update_memory=False)
        return [
            int(snap.get("steps", 0)),
            int(1e3 * snap.get("step_p50_ms", 0.0)),
            int(1e3 * snap.get("step_p90_ms", 0.0)),
            int(1e3 * snap.get("step_max_ms", 0.0)),
            int(1e3 * snap.get("records_per_s", 0.0)),
            int(1e3 * prof.get("phase_data_wait_ms", 0.0)),
            int(1e3 * prof.get("phase_h2d_ms", 0.0)),
            int(1e3 * prof.get("phase_compute_ms", 0.0)),
        ]

    @classmethod
    def _decode_exchange_row(cls, row) -> Dict[str, Any]:
        """Back to the heartbeat-payload schema (ms / records-per-s)."""
        vals = dict(zip(cls._EXCHANGE_FIELDS, (int(v) for v in row)))
        out: Dict[str, Any] = {"steps": vals["steps"]}
        if vals["steps"]:
            out.update(
                step_p50_ms=round(vals["step_p50_us"] / 1e3, 3),
                step_p90_ms=round(vals["step_p90_us"] / 1e3, 3),
                step_max_ms=round(vals["step_max_us"] / 1e3, 3),
                records_per_s=round(vals["records_per_s_milli"] / 1e3, 3),
            )
        for us_key, ms_key in (
            ("phase_data_wait_us", "phase_data_wait_ms"),
            ("phase_h2d_us", "phase_h2d_ms"),
            ("phase_compute_us", "phase_compute_ms"),
        ):
            if vals[us_key]:
                out[ms_key] = round(vals[us_key] / 1e3, 3)
        return out

    def _exchange_member_stats(self) -> None:
        """COLLECTIVE: every process contributes its local stats row via
        the cohort's allgather channel (parallel/elastic.py — the same
        int32-halved int64 wire the control broadcast rides); the leader
        keeps the follower rows for the next heartbeat's MemberBeats.

        Called at the end of every TRAINING task body, a point all
        processes reach in lockstep (the task_type gate branches
        identically everywhere — the control vector is shared state).
        Closes PR 7's "follower->leader channel" future-work note. A
        failed collective degrades the members to leader-coalesced
        telemetry, never the task."""
        if self.ctx.num_processes <= 1:
            return
        try:
            rows = self.ctx.allgather_ints(self._exchange_row())
        except Exception:
            logger.warning(
                "member-stats allgather failed; member beats fall back to "
                "leader-coalesced", exc_info=True,
            )
            return
        if not self.ctx.is_leader:
            return
        fresh: Dict[int, Dict[str, Any]] = {}
        for idx in range(1, min(len(rows), self.ctx.num_processes)):
            fresh[idx] = self._decode_exchange_row(rows[idx])
        self._member_stats = fresh   # atomic swap; heartbeat thread reads

    def _heartbeat_loop(self) -> None:
        from elasticdl_tpu.observability import timeseries as timeseries_lib

        while not self._shutdown.is_set():
            # interval-gated time-series sample (normally a clock read)
            timeseries_lib.get_store().maybe_sample()
            try:
                # optional telemetry metadata; a payload failure degrades
                # this beat to liveness-only (same contract as worker.py)
                try:
                    md = ((STATS_METADATA_KEY,
                           encode_stats(self._stats_payload())),)
                except Exception:
                    md = None
                try:
                    members = self._member_beats()
                except Exception:
                    members = []    # member telemetry never costs the beat
                resp = self._stub.Heartbeat(
                    pb.HeartbeatRequest(
                        worker_id=self.worker_id,
                        model_version=self._model_version,
                        members=members,
                    ),
                    timeout=10,
                    metadata=md,
                )
                if resp.shutdown:
                    if resp.job_done:
                        self._job_done = True
                    self._shutdown.set()
                    break
                if resp.should_checkpoint:
                    # honored by the next control vector's FLAG_CHECKPOINT —
                    # the save itself is collective and happens at the task
                    # boundary on every process
                    self._ckpt_requested = True
                if resp.learning_rate > 0:
                    # rides the next control vector (lr_bits) so every
                    # process applies it at the same task boundary
                    self._pushed_lr = resp.learning_rate
            except Exception as e:
                logger.warning("cohort heartbeat failed: %s", e)
                if not self._maybe_reconnect(e):
                    self._master_unreachable()
            # jittered beat (shared helper): cohorts relaunched together
            # must not arrive at the master in phase every interval
            self._shutdown.wait(jittered(self.cfg.worker_heartbeat_s))

    def request_preempt(self) -> bool:
        """Leader SIGTERM hook (signal-handler safe: sets a flag, no I/O).
        Returns True when this process can drain the cohort — the next
        control vector becomes OP_ABORT|FLAG_CHECKPOINT, a COLLECTIVE save
        every process joins before exiting EX_TEMPFAIL. Returns False on
        followers (caller should exit immediately; see module docstring).
        The in-flight task completes first, so the drain window is bounded
        by one task — within k8s's default 30 s grace for the task sizes
        the dispatcher hands out, and a lost race just degrades to the
        old relaunch-and-restore path."""
        if not self.ctx.is_leader:
            return False
        self._preempt = True
        return True

    def _lease_control(self) -> List[int]:
        """Leader: turn the next master response into a control vector."""
        if self._preempt and not self._shutdown.is_set():
            logger.info("leader preempted: draining cohort via collective "
                        "checkpoint")
            ctrl = [OP_ABORT] + [0] * (CTRL_LEN - 1)
            ctrl[6] = FLAG_CHECKPOINT
            return ctrl
        if self._shutdown.is_set():
            ctrl = [OP_DONE if self._job_done else OP_ABORT] + [0] * (CTRL_LEN - 1)
            if self._master_lost:
                # the heartbeat thread crossed the unreachable limit while a
                # task was running: same final-collective-save semantics as
                # the GetTask-path abort below (the save needs no master)
                ctrl[6] = FLAG_CHECKPOINT
            return ctrl
        if self._lease_queue:
            # drain locally held leases (batched GetTask) before re-polling
            task = self._lease_queue.popleft()
        else:
            try:
                resp = self._stub.GetTask(
                    pb.GetTaskRequest(
                        worker_id=self.worker_id,
                        max_tasks=self.cfg.task_lease_batch,
                    ),
                    timeout=30,
                )
            except Exception as e:
                logger.warning("cohort get_task failed: %s", e)
                if self._maybe_reconnect(e):
                    # master restarted; handshake landed — the cohort stays
                    # up and the next control vector re-leases under the
                    # new generation
                    return [OP_NOOP] + [0] * (CTRL_LEN - 1)
                if self._master_unreachable():
                    # carry FLAG_CHECKPOINT: we sit at a clean task boundary
                    # and the collective save needs no master, so a
                    # partitioned-but-relaunched cohort resumes here instead
                    # of redoing up to checkpoint_steps of work (same path
                    # as the SIGTERM drain)
                    ctrl = [OP_ABORT] + [0] * (CTRL_LEN - 1)
                    ctrl[6] = FLAG_CHECKPOINT
                    return ctrl
                return [OP_NOOP] + [0] * (CTRL_LEN - 1)
            if resp.job_done:
                self._job_done = True
                return [OP_DONE] + [0] * (CTRL_LEN - 1)
            # old master: `tasks` empty, fall back to the singular field
            leased = list(resp.tasks) or [resp.task]
            task = leased[0]
            self._lease_queue.extend(leased[1:])
        if task.type == pb.WAIT:
            return [OP_NOOP] + [0] * (CTRL_LEN - 1)
        due = (
            self.cfg.checkpoint_steps > 0
            and self._state is not None
            and self._state.model_version - self._last_ckpt_step
            >= self.cfg.checkpoint_steps
        )
        if self._ckpt_requested:
            # clear only when consumed: an unconditional clear could drop a
            # request the heartbeat thread set between read and clear, and
            # the servicer's should_checkpoint bit is one-shot
            self._ckpt_requested = False
            due = True
        return [
            OP_TASK, task.task_id, task.type,
            (
                0 if task.type == pb.SAVE_MODEL
                else self._shard_index(task.type, task.shard_name)
            ),
            task.start, task.end,
            FLAG_CHECKPOINT if due else 0,
            task.eval_job_id,
            _lr_to_bits(self._pushed_lr),
        ]

    # ------------------------------------------------------------------ #
    # rescale fast path: speculative neighbor-world compilation

    def _maybe_start_speculative_compiler(self) -> None:
        """Steady state reached (first training batches ran): start the
        background precompiler for neighbor world sizes — N±1 plus any size
        the master's pending-membership signal announces — so the reform,
        when it lands, finds its executables already in the in-memory cache
        (same process: in-place/test worlds) or the persistent on-disk
        cache (re-formed processes). Opt-in via --speculative_compile;
        everything here is best-effort and must never take training down.

        Scale-up caveat: a larger world's devices may not be visible from
        this process (real multi-host TPU) — those sizes are skipped, and
        the persistent cache populated by the first post-reform process
        is the warmth mechanism instead."""
        if (
            self._spec_compiler is not None
            or not self.cfg.speculative_compile
            or self._example_host_batch is None
            or self._state is None
        ):
            return
        import jax

        from elasticdl_tpu.training import compile_cache as cc

        local = max(1, len(jax.local_devices()))
        total = len(jax.devices())
        example = self._example_host_batch
        k = max(1, self.cfg.steps_per_dispatch)
        cfg, spec = self.cfg, self._spec

        def compile_for_size(size: int) -> None:
            need = size * local
            if need < 1 or need > total:
                raise cc.SpeculativeCompiler.SkipSize(
                    f"world size {size} needs {need} devices, "
                    f"{total} visible"
                )
            from elasticdl_tpu.parallel.mesh import build_job_mesh
            from elasticdl_tpu.training.trainer import Trainer

            mesh = build_job_mesh(cfg, jax.devices()[:need])
            trainer = Trainer(
                spec, mesh, remat=cfg.remat, remat_policy=cfg.remat_policy,
                grad_accum=cfg.grad_accum_steps, seed=cfg.shuffle_seed,
                cache_token=cc.job_cache_token(cfg),
            )
            # execution-free: lower+compile against abstract state/batch —
            # never runs anything on the neighbor mesh (whose peers, in a
            # real multi-process world, would not be there to collectivize)
            abs_state = trainer.abstract_train_state(example)
            trainer.aot_compile_train_step(
                abs_state, example, speculative=True, abstract=True)
            if k > 1:
                from elasticdl_tpu.parallel.mesh import abstract_batch_stack

                trainer.aot_compile_train_many(
                    abs_state,
                    abstract_batch_stack(mesh, example, k,
                                         spec.batch_partition),
                    speculative=True,
                )

        self._spec_compiler = cc.SpeculativeCompiler(
            compile_for_size,
            self.ctx.num_processes,
            signal_path=os.environ.get(membership_signal.ENV_VAR, ""),
            poll_s=max(1.0, self.cfg.worker_heartbeat_s / 2),
        )
        self._spec_compiler.start()
        logger.info(
            "speculative compiler started (world size %d, candidates %s)",
            self.ctx.num_processes, self._spec_compiler.candidate_sizes(),
        )

    # ------------------------------------------------------------------ #
    # collective task execution (every process)

    def _process_predictions(self, outputs, host_batch) -> None:
        """Collective: allgather the sharded prediction outputs so the
        leader holds the full batch, then run the user's processor there
        (reference parity: BasePredictionOutputsProcessor.process(outputs,
        worker_id) per worker — the cohort IS one logical worker, so its
        predictions flow through one processor on the leader)."""
        processor = self._spec.prediction_outputs_processor
        if processor is None:
            return
        import jax

        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            # collective — every process participates, leader consumes
            full = multihost_utils.process_allgather(outputs)
        else:
            full = jax.device_get(outputs)
        if not self.ctx.is_leader:
            return
        from elasticdl_tpu.worker.prediction_outputs_processor import (
            mask_predictions,
        )

        valid = np.asarray(host_batch["mask"]) > 0
        # pytree-safe: predict outputs may be a dict/tuple, not an array
        processor.process(mask_predictions(full, valid), self.worker_id)

    def _maybe_apply_ctrl_lr(self) -> None:
        """Apply the latest ctrl-carried LR override once state exists.
        Called at the task boundary AND after _ensure_state: a relaunched
        cohort builds state lazily from a pre-push checkpoint (stale LR in
        its opt_state), and must not run its whole first task on it. Every
        process reaches the same call sites with the same ctrl value, so
        lockstep holds; a non-modulated optimizer logs instead of crashing
        (deterministically on all processes)."""
        pushed_lr = self._ctrl_pushed_lr
        if pushed_lr > 0 and pushed_lr != self._applied_push_lr and \
                self._state is not None:
            from elasticdl_tpu.training.lr_modulation import (
                apply_learning_rate,
            )

            self._state = apply_learning_rate(
                self._trainer, self._state, pushed_lr)
            self._applied_push_lr = pushed_lr
            logger.info("applied master-pushed LR %g", pushed_lr)

    def _run_task(self, ctrl: List[int]) -> None:
        import jax

        self._phase = "train"
        try:
            self._run_task_inner(ctrl, jax)
        finally:
            self._phase = "idle"

    def _run_task_inner(self, ctrl: List[int], jax) -> None:
        _, task_id, task_type, shard_idx, start, end, flags, eval_job, lr_bits = ctrl
        self._ctrl_pushed_lr = _bits_to_lr(lr_bits)
        self._maybe_apply_ctrl_lr()
        if task_type == pb.SAVE_MODEL:
            # The master's final exclusive save task: a collective checkpoint
            # (every process writes its addressable shards), leader reports.
            # With no live state (relaunched cohort, no batch processed yet)
            # success is only true if a checkpoint already exists on disk —
            # it IS the current state then; otherwise report failure so the
            # dispatcher retries (all processes branch identically: state
            # and the checkpoint dir are symmetric across the cohort).
            mngr = self._checkpoint_manager()
            ok, err = True, ""
            if mngr is None:
                # No checkpoint_dir: nothing can be persisted. Reporting
                # success would retire the job's durability task with
                # nothing saved — fail it so the dispatcher's bounded
                # retries surface the misconfiguration (all processes
                # branch identically: the config is cohort-symmetric).
                ok, err = False, "no checkpoint_dir configured, nothing to save to"
            elif self._state is not None:
                mngr.save(self._state, wait=True)
                self._last_ckpt_step = self._state.model_version
            elif mngr.latest_step(refresh=True) is None:
                ok, err = False, "no live state and no checkpoint on disk"
            if self.ctx.is_leader:
                try:
                    self._stub.ReportTaskResult(
                        pb.ReportTaskResultRequest(
                            worker_id=self.worker_id, task_id=task_id,
                            success=ok, err_message=err,
                            model_version=(
                                self._state.model_version
                                if self._state is not None else 0
                            ),
                        ),
                        timeout=30,
                    )
                except Exception as e:
                    logger.warning(
                        "cohort report failed for save task %d: %s", task_id, e
                    )
                    self._maybe_reconnect(e)
            return
        svc = self._data_service(task_type)
        shard = self._shard_name(task_type, shard_idx)
        loss_sum, loss_count = 0.0, 0
        step_time_sum = 0.0
        metric_states = None
        k = max(1, self.cfg.steps_per_dispatch)
        buf: List[Any] = []   # host batches awaiting one grouped dispatch

        def flush_training_group():
            """Run the buffered host batches: one train_many dispatch for a
            full k-group (every process dispatches the identical program —
            collective), single steps for a trailing partial (so only two
            compiled programs exist, not one per remainder length).

            EVERY process forces its local view of the dispatch (the
            leader via float(loss), followers via block_until_ready) so
            follower step times are REAL wall times — the train step is a
            lockstep collective, so the follower sync completes with the
            leader's and costs no extra device time; what it buys is each
            process's own host-side/data-path skew showing up in ITS
            telemetry (the member-stats exchange ships it to the leader).
            """
            nonlocal loss_sum, loss_count, step_time_sum
            if not buf:
                return
            import jax
            import jax.numpy as jnp

            prof = profile_lib.get_profiler()
            # batch assembly stays OUTSIDE the timed region — step_time_ms
            # has always meant dispatch + device compute, and host-side
            # stack/H2D would otherwise read as a phantom slowdown (the
            # profiler books it under h2d instead)
            if len(buf) == k and k > 1:
                with prof.phase("h2d"):
                    stacked = make_global_batch_stack(
                        self._mesh, buf, self._spec.batch_partition
                    )
                t0 = time.perf_counter()
                self._state, m = self._trainer.train_many(self._state, stacked)
                if self.ctx.is_leader:
                    loss_sum += float(jnp.sum(m["loss"]))
                else:
                    # follower-local completion barrier (see docstring):
                    # edl-lint: disable=EDL201
                    jax.block_until_ready(m["loss"])
            else:
                with prof.phase("h2d"):
                    globals_ = [
                        make_global_batch(
                            self._mesh, b, self._spec.batch_partition)
                        for b in buf
                    ]
                t0 = time.perf_counter()
                for gb in globals_:
                    self._state, logs = self._trainer.train_step(
                        self._state, gb)
                    if self.ctx.is_leader:
                        # deliberate sync: forces the collective dispatch so
                        # step_time is honest (see comment below):
                        # edl-lint: disable=EDL201
                        loss_sum += float(logs["loss"])
                    else:
                        # follower twin of the leader's float():
                        # edl-lint: disable=EDL201
                        jax.block_until_ready(logs["loss"])
            # wall time covers dispatch + device compute on THIS process
            # (every process forced its own view above)
            group_s = time.perf_counter() - t0
            if self.ctx.is_leader:
                step_time_sum += group_s
                loss_count += len(buf)
            # per-step telemetry sample for the heartbeat payload / the
            # member-stats exchange (the whole cohort advances
            # minibatch_size rows per step)
            self._step_stats.observe_step(
                group_s / max(1, len(buf)), self.cfg.minibatch_size
            )
            prof.add("compute", group_s)
            prof.step_done(len(buf))
            self._model_version += len(buf)
            buf.clear()

        pred_buf: List[Any] = []

        def flush_predict_group():
            """Prediction twin: a full k-group is ONE collective
            predict_many dispatch; each batch's (sharded) output slice then
            allgathers through _process_predictions in order. Trailing
            partials run as single collective predict_steps."""
            if not pred_buf:
                return
            if len(pred_buf) == k and k > 1:
                outs = self._trainer.predict_many(
                    self._state,
                    make_global_batch_stack(
                        self._mesh, pred_buf, self._spec.batch_partition),
                )
                for i, hb in enumerate(pred_buf):
                    # tree-indexed: outs leaves carry the group dim, and
                    # predict outputs may be a dict/tuple pytree
                    self._process_predictions(
                        jax.tree_util.tree_map(lambda x, i=i: x[i], outs), hb
                    )
            else:
                for hb in pred_buf:
                    gb = make_global_batch(
                        self._mesh, hb, self._spec.batch_partition)
                    self._process_predictions(
                        self._trainer.predict_step(self._state, gb), hb)
            pred_buf.clear()

        eval_buf: List[Any] = []

        def flush_eval_group(states):
            """Eval twin of flush_training_group: a full k-group is ONE
            collective eval_many dispatch on every process; a trailing
            partial runs as single collective eval_steps."""
            if not eval_buf:
                return states
            if states is None:
                states = self._trainer.new_metric_states()
            if len(eval_buf) == k and k > 1:
                states = self._trainer.eval_many(
                    self._state,
                    make_global_batch_stack(
                        self._mesh, eval_buf, self._spec.batch_partition),
                    states,
                )
            else:
                for b in eval_buf:
                    states = self._trainer.eval_step(
                        self._state,
                        make_global_batch(
                            self._mesh, b, self._spec.batch_partition),
                        states,
                    )
            eval_buf.clear()
            return states

        from elasticdl_tpu.data.prefetch import _wire_cast

        # data-wait attribution: blocking on the reader/parse pipeline is
        # this process's OWN input path (exactly what the follower-local
        # exchange exists to surface)
        for host_batch in profile_lib.timed_iter(
            svc.batches(shard, start, end), profile_lib.get_profiler()
        ):
            # same bf16 wire compression the single-process worker applies
            # (mask exempted by _wire_cast; cohort reports count by span,
            # not mask, so accounting is unaffected either way)
            host_batch = _wire_cast(host_batch, self.cfg.wire_dtype)
            if task_type == pb.TRAINING and self._example_host_batch is None:
                # the speculative compiler's example input: post-cast, so
                # neighbor-world programs lower with the real wire dtypes
                self._example_host_batch = host_batch
            if task_type == pb.TRAINING:
                if self._state is None:
                    self._ensure_state(make_global_batch(
                        self._mesh, host_batch, self._spec.batch_partition))
                    self._maybe_apply_ctrl_lr()
                buf.append(host_batch)
                if len(buf) == k:
                    flush_training_group()
                continue
            if k > 1 and task_type in (pb.EVALUATION, pb.PREDICTION):
                # grouped eval/prediction: same collective scan dispatch on
                # every process, mirroring training groups
                if self._state is None:
                    self._ensure_state(make_global_batch(
                        self._mesh, host_batch, self._spec.batch_partition))
                    self._maybe_apply_ctrl_lr()
                if task_type == pb.EVALUATION:
                    eval_buf.append(host_batch)
                    if len(eval_buf) == k:
                        metric_states = flush_eval_group(metric_states)
                else:
                    pred_buf.append(host_batch)
                    if len(pred_buf) == k:
                        flush_predict_group()
                continue
            batch = make_global_batch(
                self._mesh, host_batch, self._spec.batch_partition
            )
            self._ensure_state(batch)
            self._maybe_apply_ctrl_lr()
            if task_type == pb.PREDICTION:
                outputs = self._trainer.predict_step(self._state, batch)
                self._process_predictions(outputs, host_batch)
            else:
                if metric_states is None:
                    metric_states = self._trainer.new_metric_states()
                metric_states = self._trainer.eval_step(
                    self._state, batch, metric_states
                )
        flush_training_group()   # trailing partial group (single steps)
        metric_states = flush_eval_group(metric_states)  # trailing partial
        flush_predict_group()                            # trailing partial

        if task_type == pb.TRAINING:
            # COLLECTIVE member-stats exchange at the task boundary (every
            # process reaches this point in lockstep; the task_type gate
            # branches identically everywhere): followers' real step times
            # land on the leader for the next heartbeat's MemberBeats
            self._exchange_member_stats()

        # every process — followers included — samples its own time-series
        # ring at the task boundary (interval-gated: a clock read when not
        # due). The leader additionally samples from its heartbeat thread;
        # followers have no heartbeat, so this is their only cadence.
        from elasticdl_tpu.observability import timeseries as timeseries_lib

        timeseries_lib.get_store().maybe_sample()

        if flags & FLAG_CHECKPOINT:
            mngr = self._checkpoint_manager()
            if mngr is not None and self._state is not None:
                # collective: every process writes its addressable shards
                mngr.save(self._state, wait=True)
                self._last_ckpt_step = self._state.model_version

        if not self.ctx.is_leader:
            return
        report = pb.ReportTaskResultRequest(
            worker_id=self.worker_id, task_id=task_id, success=True,
            records_processed=end - start,
            model_version=(
                self._state.model_version if self._state is not None else 0
            ),
            loss_sum=loss_sum, loss_count=loss_count,
            step_time_sum=step_time_sum, step_count=loss_count,
        )
        try:
            self._stub.ReportTaskResult(report, timeout=30)
            if task_type == pb.EVALUATION and metric_states is not None:
                msg = pb.ReportEvaluationMetricsRequest(
                    worker_id=self.worker_id, eval_job_id=eval_job,
                    task_id=task_id,
                )
                for name, state in metric_states.items():
                    arr = np.asarray(jax.device_get(state), np.float32)
                    msg.states.append(
                        pb.MetricState(name=name, data=arr.tobytes())
                    )
                self._stub.ReportEvaluationMetrics(msg, timeout=30)
        except Exception as e:
            logger.warning("cohort report failed for task %d: %s", task_id, e)
            # fenced = the restarted master requeued this lease; re-register
            # so the next lease lands, never resend the pre-crash report
            self._maybe_reconnect(e)

    def _export_final_model(self) -> None:
        if not self.cfg.output or self._state is None:
            return
        try:
            from elasticdl_tpu.training.export import export_model

            # collective gather (process_allgather) on every process;
            # only the leader writes files
            export_model(
                self._state, self.cfg.output,
                model_def=self.cfg.model_def,
                model_params=self._spec.model_params,
                module_name=self._spec.module_name,
                write_files=self.ctx.is_leader,
            )
        except Exception:
            logger.exception("cohort final export failed")

    # ------------------------------------------------------------------ #

    def _install_sigterm_drain(self) -> None:
        """(Re-)install the preemption handler AFTER world formation:
        `jax.distributed.initialize` registers its own C++ SIGTERM handler
        (xla preemption_notifier), silently replacing anything the
        entrypoint installed earlier — so the drain handler must be
        installed here to win. No-op off the main thread."""
        import signal
        import sys as _sys

        def _on_sigterm(*_):
            if not self.request_preempt():
                _sys.exit(ExitCode.COHORT_EVICTED)

        try:
            signal.signal(signal.SIGTERM, _on_sigterm)
        except ValueError:
            pass

    def run(self) -> int:
        from elasticdl_tpu.common import membership_signal
        from elasticdl_tpu.observability import tracing
        from elasticdl_tpu.observability.http import start_server

        # observability: role + world version on every span/log; when this
        # boot IS a reform (the master's announcement carries a trace id),
        # the boot spans join the master's resize timeline
        role = f"cohort-{self.ctx.process_id}"
        tracing.configure_from_config(
            self.cfg, role=role, world_version=self.ctx.world_version
        )
        # flight recorder: every cohort process gets its own black box
        # (crash/SIGUSR2//debug/flight triggers; flight.py trigger matrix)
        flight_lib.configure_from_config(self.cfg, role=role)
        flight_lib.install_crash_hooks()
        # metrics time series: ring + rolling history for this process;
        # sampled from the leader's heartbeat loop (followers sample at
        # task boundaries via the same singleton)
        from elasticdl_tpu.observability import timeseries as timeseries_lib

        timeseries_lib.configure_from_config(self.cfg, role=role)
        reform_tid = membership_signal.trace_id()
        # a set EDL_METRICS_PORT overrides cfg.metrics_port either way
        metrics_server = start_server(
            role=role, port=self.cfg.metrics_port
        )
        try:
            # goodput: a (re-)forming world's formation + build time IS
            # the cohort flavor's rescale cost — settle (rendezvous) and
            # compile (trainer construction against the warm cache)
            with tracing.span(
                "cohort.world_form", trace_id=reform_tid,
                num_processes=self.ctx.num_processes,
                process_id=self.ctx.process_id,
            ), goodput_lib.get_ledger().phase("rescale", sub="settle"):
                self.ctx.initialize()
        except Exception:
            logger.exception(
                "world formation failed (coordinator %s, process %d/%d)",
                self.ctx.coordinator_addr, self.ctx.process_id,
                self.ctx.num_processes,
            )
            # a formation failure's last seconds (coordinator address,
            # port race, peer set) are postmortem gold — cut the box
            flight_lib.get_recorder().dump("world_form_failed")
            if metrics_server is not None:
                metrics_server.stop()
            return ExitCode.WORLD_FORM_FAILED
        self._install_sigterm_drain()
        try:
            with tracing.span("cohort.build", trace_id=reform_tid), \
                    goodput_lib.get_ledger().phase("rescale", sub="compile"):
                self._build()
            if self.ctx.is_leader:
                # the register RPC carries the reform trace id (when this
                # boot is one) to the master via gRPC metadata — the
                # cross-role join point of the resize timeline
                with tracing.span("cohort.register", trace_id=reform_tid):
                    self._connect()
                self._init_embedding_tier()
                threading.Thread(
                    target=self._heartbeat_loop, daemon=True
                ).start()
            backoff = max(0.5, self.cfg.worker_heartbeat_s / 4)
            while True:
                leader_ctrl = (
                    self._lease_control()
                    if self.ctx.is_leader
                    else [0] * CTRL_LEN
                )
                ctrl = [int(x) for x in self.ctx.broadcast_ints(leader_ctrl)]
                op = ctrl[0]
                if self.ctx.is_leader and self._tier is not None:
                    # replica delta sync at the collective poll boundary
                    # (leader-only — the tier is the leader's; cheap
                    # no-op when this cohort replicates nothing)
                    try:
                        self._tier.sync_replicas()
                    except Exception:
                        logger.exception("embedding replica sync failed")
                if op == OP_NOOP:
                    # jittered on the LEADER only (followers just follow
                    # the broadcast), so idle cohorts de-phase their
                    # polls. Goodput: idle-with-no-task is `lease_wait`.
                    with goodput_lib.get_ledger().phase("lease_wait"):
                        time.sleep(
                            jittered(backoff) if self.ctx.is_leader
                            else backoff
                        )
                    continue
                if op == OP_TASK:
                    self._run_task(ctrl)
                    # steady state (a task ran): arm the neighbor-world
                    # precompiler so a future reform lands on a warm cache
                    self._maybe_start_speculative_compiler()
                    continue
                if op in (OP_DONE, OP_ABORT):
                    if op == OP_DONE:
                        self._export_final_model()
                    break

            def finish():
                """Post-loop teardown (runs UNDER the drain checkpoint's
                async write when one is in flight — the overlap that keeps
                the final save off the critical teardown path)."""
                if self._spec_compiler is not None:
                    self._spec_compiler.stop()
                processor = (
                    self._spec.prediction_outputs_processor
                    if self._spec else None
                )
                if processor is not None:
                    # only the leader's processor ever received outputs, but
                    # close() on every process is harmless and guarantees the
                    # leader's buffered tail is flushed (base-class contract)
                    try:
                        processor.close()
                    except Exception:
                        logger.exception(
                            "prediction outputs processor close failed")
                self._shutdown.set()
                if self.ctx.is_leader:
                    try:
                        self._channel.close()
                    except Exception:
                        # teardown-only; still worth a trace for post-mortems
                        logger.debug(
                            "grpc channel close failed at exit", exc_info=True
                        )

            # the tier's shards drain on EVERY teardown path (the next
            # leader generation restores them bit-exactly, watermarks
            # included) — cheap, atomic per shard, leader-only
            self._drain_embedding_tier()
            if op == OP_ABORT and ctrl[6] & FLAG_CHECKPOINT:
                # preemption drain: one final collective save so the
                # relaunched cohort resumes at the pre-kill step. The write
                # is async and overlapped with the teardown work above —
                # save_overlapped blocks for durability before we return
                # (and before ctx.shutdown tears the world down).
                mngr = self._checkpoint_manager()
                if mngr is not None and self._state is not None:
                    mngr.save_overlapped(self._state, finish)
                    self._last_ckpt_step = self._state.model_version
                    logger.info(
                        "preemption checkpoint saved at step %d "
                        "(write overlapped with teardown)",
                        self._last_ckpt_step,
                    )
                else:
                    finish()
            else:
                finish()
            # ABORT = the master evicted us without job completion (e.g. a
            # heartbeat lapse marked the leader dead and our tasks were
            # requeued): exit EX_TEMPFAIL so the manager relaunches the
            # cohort; a clean 0 would read as success and end all watching.
            return 0 if op == OP_DONE else ExitCode.COHORT_EVICTED
        finally:
            if metrics_server is not None:
                metrics_server.stop()
            tracing.get_tracer().close()
            self.ctx.shutdown()


def run_cohort(cfg: JobConfig) -> int:
    """Build a CohortWorker with full SIGTERM wiring and run it: before
    world formation the handler is a plain EX_TEMPFAIL exit (nothing to
    drain yet); run() upgrades it to the leader drain after
    `jax.distributed.initialize` (which would otherwise clobber it — see
    `_install_sigterm_drain`). The one cohort entrypoint: anything that
    constructs CohortWorker directly gets no pre-formation handler."""
    import signal
    import sys

    worker = CohortWorker(cfg)
    try:
        signal.signal(
            signal.SIGTERM, lambda *_: sys.exit(ExitCode.COHORT_EVICTED)
        )
    except ValueError:
        pass  # not the main thread (tests driving run_cohort in-process)
    return worker.run()
