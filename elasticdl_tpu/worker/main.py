"""Worker entrypoint.

Reference parity: elasticdl/python/worker/main.py — parse the re-serialized
argv the master/launcher passed, build the Worker, run the task loop.
"""

from __future__ import annotations

import os
import signal
import sys
from typing import List, Optional

from elasticdl_tpu.common.config import JobConfig
from elasticdl_tpu.worker.worker import Worker


def main(argv: Optional[List[str]] = None) -> int:
    cfg = JobConfig.from_argv(sys.argv[1:] if argv is None else argv)
    # EDL_PROCESS_ID marks a cohort member even when dynamic resizing has
    # shrunk the world to 1 process (cfg.num_processes is the ORIGINAL size)
    if cfg.num_processes > 1 or "EDL_PROCESS_ID" in os.environ:
        # SPMD cohort member. SIGTERM: the leader drains collectively
        # (finish the in-flight task, broadcast OP_ABORT|FLAG_CHECKPOINT,
        # every process joins one final save, exit EX_TEMPFAIL); a follower
        # cannot drain — it exits EX_TEMPFAIL immediately and the manager
        # relaunches the cohort from the last checkpoint. All the signal
        # wiring lives in run_cohort/CohortWorker (worker/cohort.py).
        from elasticdl_tpu.worker.cohort import run_cohort

        return run_cohort(cfg)
    worker = Worker(cfg)
    # k8s preemption delivers SIGTERM with a grace period; drain + checkpoint
    signal.signal(signal.SIGTERM, lambda *_: worker.preempt())
    return worker.run()


if __name__ == "__main__":
    raise SystemExit(main())
