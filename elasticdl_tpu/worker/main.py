"""Worker entrypoint.

Reference parity: elasticdl/python/worker/main.py — parse the re-serialized
argv the master/launcher passed, build the Worker, run the task loop.
"""

from __future__ import annotations

import signal
import sys
from typing import List, Optional

from elasticdl_tpu.common.config import JobConfig
from elasticdl_tpu.worker.worker import Worker


def main(argv: Optional[List[str]] = None) -> int:
    cfg = JobConfig.from_argv(sys.argv[1:] if argv is None else argv)
    worker = Worker(cfg)
    # k8s preemption delivers SIGTERM with a grace period; drain + checkpoint
    signal.signal(signal.SIGTERM, lambda *_: worker.preempt())
    return worker.run()


if __name__ == "__main__":
    raise SystemExit(main())
