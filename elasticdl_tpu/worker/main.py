"""Worker entrypoint.

Reference parity: elasticdl/python/worker/main.py — parse the re-serialized
argv the master/launcher passed, build the Worker, run the task loop.
"""

from __future__ import annotations

import sys
from typing import List, Optional

from elasticdl_tpu.common.config import JobConfig
from elasticdl_tpu.worker.worker import Worker


def main(argv: Optional[List[str]] = None) -> int:
    cfg = JobConfig.from_argv(sys.argv[1:] if argv is None else argv)
    return Worker(cfg).run()


if __name__ == "__main__":
    raise SystemExit(main())
